"""C++ host runtime tests: codec round-trip, consistent parity, and the
three-way differential (native == local == jax) on identical randomness."""

import ctypes

import jax
import numpy as np
import pytest

from qba_tpu.backends.local_backend import _consistent, run_trial_local
from qba_tpu.config import QBAConfig

native = pytest.importorskip("qba_tpu.native")
if not native.available():  # pragma: no cover - g++ is expected in CI
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from qba_tpu.backends.native_backend import run_trial_native  # noqa: E402

_i32p = ctypes.POINTER(ctypes.c_int32)


def _as_i32(a):
    a = np.ascontiguousarray(a, dtype=np.int32)
    return a, a.ctypes.data_as(_i32p)


class TestCodec:
    def _roundtrip(self, p, v, tuples):
        lib = native.load()
        max_len = max((len(t) for t in tuples), default=1) or 1
        nt = len(tuples)
        tm = np.zeros((max(nt, 1), max_len), dtype=np.int32)
        lens = np.zeros(max(nt, 1), dtype=np.int32)
        for i, t in enumerate(tuples):
            lens[i] = len(t)
            tm[i, : len(t)] = t
        cap = 3 + len(p) + nt * (1 + max_len)
        buf = np.zeros(cap, dtype=np.int32)
        p_a, p_p = _as_i32(np.asarray(p, dtype=np.int32))
        tm_a, tm_p = _as_i32(tm)
        lens_a, lens_p = _as_i32(lens)
        buf_p = buf.ctypes.data_as(_i32p)
        n = lib.qba_encode_pvl(p_p, len(p), v, tm_p, lens_p, nt, max_len, buf_p, cap)
        assert n > 0

        p_out = np.zeros(max(len(p), 1), dtype=np.int32)
        t_out = np.zeros((max(nt, 1), max_len), dtype=np.int32)
        l_out = np.zeros(max(nt, 1), dtype=np.int32)
        hdr = np.zeros(3, dtype=np.int32)
        used = lib.qba_decode_pvl(
            buf_p, n, p_out.ctypes.data_as(_i32p), len(p),
            t_out.ctypes.data_as(_i32p), l_out.ctypes.data_as(_i32p),
            nt, max_len, hdr.ctypes.data_as(_i32p),
        )
        assert used == n
        assert hdr[1] == v and hdr[0] == len(p) and hdr[2] == nt
        assert p_out[: len(p)].tolist() == list(p)
        got = {tuple(t_out[i, : l_out[i]].tolist()) for i in range(nt)}
        assert got == {tuple(t) for t in tuples}

    def test_roundtrip(self):
        self._roundtrip([1, 4, 9], 3, [(2, 5), (7, 1)])

    def test_roundtrip_empty(self):
        self._roundtrip([], 0, [])

    def test_malformed_rejected(self):
        lib = native.load()
        # |P| = 100 but only 2 words follow
        bad = np.array([100, 1, 2], dtype=np.int32)
        out = np.zeros(8, dtype=np.int32)
        hdr = np.zeros(3, dtype=np.int32)
        rc = lib.qba_decode_pvl(
            bad.ctypes.data_as(_i32p), 3, out.ctypes.data_as(_i32p), 8,
            out.ctypes.data_as(_i32p), out.ctypes.data_as(_i32p), 2, 4,
            hdr.ctypes.data_as(_i32p),
        )
        assert rc == -1


class TestConsistentParity:
    def test_random_cases_match_python(self):
        lib = native.load()
        rng = np.random.default_rng(0)
        w = 4
        for _ in range(300):
            nt = int(rng.integers(0, 4))
            n = int(rng.integers(1, 4))
            same_len = rng.random() < 0.7
            tuples = []
            for _t in range(nt):
                ln = n if same_len else int(rng.integers(1, 4))
                tuples.append(tuple(int(x) for x in rng.integers(0, w + 1, ln)))
            v = int(rng.integers(0, w))
            expected = _consistent(v, set(tuples), w)

            uniq = sorted(set(tuples))
            max_len = max((len(t) for t in uniq), default=1) or 1
            tm = np.zeros((max(len(uniq), 1), max_len), dtype=np.int32)
            lens = np.zeros(max(len(uniq), 1), dtype=np.int32)
            for i, t in enumerate(uniq):
                lens[i] = len(t)
                tm[i, : len(t)] = t
            got = lib.qba_consistent(
                v, tm.ctypes.data_as(_i32p), lens.ctypes.data_as(_i32p),
                len(uniq), max_len, w,
            )
            assert bool(got) == expected, (v, tuples)


CONFIGS = [
    QBAConfig(n_parties=3, size_l=8, n_dishonest=0),
    QBAConfig(n_parties=3, size_l=8, n_dishonest=1),
    QBAConfig(n_parties=3, size_l=8, n_dishonest=3),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2),
    QBAConfig(n_parties=11, size_l=16, n_dishonest=5),
]


class TestDifferentialNativeVsLocal:
    @pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"p{c.n_parties}d{c.n_dishonest}")
    def test_matches_local(self, cfg):
        keys = jax.random.split(jax.random.key(11), 6)
        for k in keys:
            a = run_trial_native(cfg, k)
            b = run_trial_local(cfg, k)
            assert a == b

    def test_matches_jax_engine(self):
        # local == jax is covered by test_differential; close the triangle
        # native == jax directly on one adversarial config.
        from qba_tpu.rounds import run_trial

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        for k in jax.random.split(jax.random.key(5), 4):
            a = run_trial_native(cfg, k)
            r = jax.jit(lambda kk: run_trial(cfg, kk))(k)
            assert a["decisions"] == [int(x) for x in np.asarray(r.decisions)]
            assert a["success"] == bool(np.asarray(r.success))
            assert a["honest"] == [bool(h) for h in np.asarray(r.honest)]


class TestThreadedExecutor:
    def test_batch_matches_per_trial(self):
        # The threaded batch executor must reproduce the per-trial native
        # runs exactly (same key tree, pure per-trial function).
        from qba_tpu.backends.jax_backend import trial_keys
        from qba_tpu.backends.native_backend import (
            run_trial_native,
            run_trials_native,
        )

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=12)
        keys = trial_keys(cfg)
        batch = run_trials_native(cfg, keys, n_threads=4)
        for i in range(cfg.trials):
            one = run_trial_native(cfg, keys[i])
            assert batch["decisions"][i].tolist() == one["decisions"]
            assert bool(batch["success"][i]) == one["success"]
            got_vi = [
                {int(x) for x in range(cfg.w) if batch["vi"][i, j, x]}
                for j in range(cfg.n_lieutenants)
            ]
            assert got_vi == one["vi"]

    def test_batch_matches_jax_backend(self):
        from qba_tpu.backends.jax_backend import run_trials, trial_keys
        from qba_tpu.backends.native_backend import run_trials_native

        cfg = QBAConfig(n_parties=4, size_l=8, n_dishonest=1, trials=16)
        keys = trial_keys(cfg)
        a = run_trials(cfg, keys)
        b = run_trials_native(cfg, keys)
        assert np.asarray(a.trials.decisions).tolist() == b["decisions"].tolist()
        assert abs(float(a.success_rate) - b["success_rate"]) < 1e-6


class TestNativeEventTrail:
    """The C engine's trace buffer renders the same protocol event
    grammar the local backend emits (VERDICT r1 #3: the trail must come
    from the message-level backends — both of them)."""

    def _trails(self, cfg, seed=0):
        import jax

        from qba_tpu.backends.local_backend import run_trial_local
        from qba_tpu.backends.native_backend import run_trial_native
        from qba_tpu.obs import EventLog, Level

        key = jax.random.key(seed)
        log_l, log_n = EventLog(Level.DEBUG), EventLog(Level.DEBUG)
        rl = run_trial_local(cfg, key, log=log_l, trial=0)
        rn = run_trial_native(cfg, key, log=log_n, trial=0)
        assert rl["decisions"] == rn["decisions"]
        return log_l.events, log_n.events

    @pytest.mark.parametrize(
        "cfg",
        [
            QBAConfig(n_parties=3, size_l=8, n_dishonest=1),
            QBAConfig(n_parties=5, size_l=16, n_dishonest=2),
            QBAConfig(
                n_parties=5, size_l=16, n_dishonest=2,
                attack_scope="broadcast",
            ),
            QBAConfig(
                n_parties=4, size_l=8, n_dishonest=1,
                delivery="racy", p_late=0.4,
            ),
            # The defer mechanism (VERDICT r2 item 5): late packets
            # carry over a round in BOTH message-level engines; the
            # trails must match including the deferred re-deliveries.
            QBAConfig(
                n_parties=5, size_l=16, n_dishonest=2,
                delivery="racy", p_late=0.5, racy_mode="defer",
            ),
            # w = 32 exceeds a 31-bit vi mask: pins the list-form
            # kind-7/8 snapshot records.
            QBAConfig(n_parties=16, size_l=8, n_dishonest=2),
        ],
        ids=lambda c: f"p{c.n_parties}_d{c.n_dishonest}_{c.attack_scope[:5]}_{c.delivery}",
    )
    def test_trails_match_local_backend(self, cfg):
        ev_l, ev_n = self._trails(cfg)

        def norm(events):
            # Compare the protocol content: (phase, message, fields).
            return [(e.phase, e.message, e.fields) for e in events]

        a, b = norm(ev_l), norm(ev_n)
        assert len(a) == len(b), (len(a), len(b))
        for i, (x, y) in enumerate(zip(a, b)):
            assert x == y, f"event {i}: local={x} native={y}"

    def test_trail_covers_reference_mpi_print_sites(self):
        # The reference logs: dishonesty (tfg.py:124), received lists
        # (:159-162), commander state (:328-330), packet sends (:203,229),
        # attack actions (:275-284), receives (:190,294), and the verdict
        # triple (:360-363).  A dishonest run's native trail must cover
        # every message kind.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        for seed in range(6):
            _, ev = self._trails(cfg, seed=seed)
            got = {(e.phase, e.message) for e in ev}
            want = {
                ("dishonesty", "party role"),
                ("particles", "list received"),
                ("step2", "commander order"),
                ("step2", "send"),
                ("step3a", "receive"),
                ("round", "receive"),
                ("round", "vi"),
                ("decision", "verdict"),
            }
            assert want <= got, want - got
            if ("round", "attack") in got and ("round", "send") in got:
                break
        else:
            pytest.fail("no seed produced attack + rebroadcast events")
