"""Seeded KI-2 violation: an explicit ``tiled_block`` override that
divides its pool (so :class:`~qba_tpu.config.QBAConfig` accepts it)
but busts the verdict kernel's VMEM pre-filter budget.  Off-TPU
resolution honors the override unchecked — only the lint's static
plan audit stands between this config and CPU tests modeling a plan
the TPU would reject.
"""

from qba_tpu.config import QBAConfig


def bad_config() -> QBAConfig:
    # North-star shape: pool = 32 * 64 = 2048; block 256 tiles it
    # exactly but its VMEM estimate (~88 MiB) is nearly double the
    # 48 MiB _TILED_PREFILTER_BYTES budget.
    return QBAConfig(33, 64, 10, tiled_block=256)
