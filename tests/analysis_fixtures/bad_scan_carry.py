"""Seeded KI-5 violation: an undonated round-scan carry.

The round engines carry the mailbox pool through a ``lax.scan`` whose
body launches a kernel; the shipped kernels hand the carried HBM
buffer back via ``input_output_aliases``.  This fixture is the same
shape *without* the alias — every iteration allocates a fresh
generation of the carry, which on TPU silently halves the KI-2 trial
ceiling (two resident pool generations) and adds a copy per round.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _bump_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] + 1.0


def _step(pool, donate: bool):
    aliases = {0: 0} if donate else {}
    return pl.pallas_call(
        _bump_kernel,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases=aliases,
        interpret=True,
    )(pool)


def undonated_round_loop(pool):
    """Kernel-in-scan with NO alias onto the carry output: KI-5
    scan-carry finding."""

    def body(carry, _):
        return _step(carry, donate=False), ()

    final, _ = jax.lax.scan(body, pool, (), length=3)
    return final


def donated_round_loop(pool):
    """The shipped form: the carry aliases the kernel input."""

    def body(carry, _):
        return _step(carry, donate=True), ()

    final, _ = jax.lax.scan(body, pool, (), length=3)
    return final


def example_pool():
    return jnp.zeros((8, 128), jnp.float32)
