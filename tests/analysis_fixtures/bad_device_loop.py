"""Seeded KI-6 violation: a host callback inside a device loop body.

``leaky_loop`` builds the same shape of sequential program as the
shipped ``sweep._device_loop_foldin`` — a ``lax.while_loop`` whose
condition is the stopping predicate — but its body reports progress
through ``jax.debug.callback``, a host round trip per chunk.  That is
exactly the failure mode the ``check_device_loop`` obligations exist
to catch: the single-dispatch contract is void if any iteration can
re-enter the host, fenced or not.

``clean_loop`` is the shipped discipline: the body stays
transfer-free and the host reads the carry back exactly once, after
the loop returns.
"""

import jax
import jax.numpy as jnp


def _chunk_body(seed, i, chunk_trials):
    """A stand-in engine chunk: a round ``scan`` over folded-in keys,
    reduced to a success count — structurally what the real loop body
    dispatches."""
    key = jax.random.fold_in(jax.random.key(seed), i)
    bits = jax.random.bernoulli(key, 0.5, (chunk_trials,))

    def round_step(carry, b):
        return carry + b.astype(jnp.int32), None

    k, _ = jax.lax.scan(round_step, jnp.int32(0), bits)
    return k


def leaky_loop(seed, n_chunks, chunk_trials, lo, hi):
    """KI-6 device-loop finding: per-chunk host callback in the body."""

    def cond(c):
        i, k_total, _ = c
        return (i < n_chunks) & ~((k_total <= lo[i]) | (k_total >= hi[i]))

    def body(c):
        i, k_total, counts = c
        k = _chunk_body(seed, i, chunk_trials)
        jax.debug.callback(lambda kk: None, k)  # the leak
        return (i + 1, k_total + k, counts.at[i].set(k))

    carry = (jnp.int32(0), jnp.int32(0), jnp.zeros(n_chunks, jnp.int32))
    return jax.lax.while_loop(cond, body, carry)


def clean_loop(seed, n_chunks, chunk_trials, lo, hi):
    """The shipped form: a transfer-free body; one readback after."""

    def cond(c):
        i, k_total, _ = c
        return (i < n_chunks) & ~((k_total <= lo[i]) | (k_total >= hi[i]))

    def body(c):
        i, k_total, counts = c
        k = _chunk_body(seed, i, chunk_trials)
        return (i + 1, k_total + k, counts.at[i].set(k))

    carry = (jnp.int32(0), jnp.int32(0), jnp.zeros(n_chunks, jnp.int32))
    return jax.lax.while_loop(cond, body, carry)
