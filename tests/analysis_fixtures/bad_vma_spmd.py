"""Seeded KI-1 violation: the literal round-4 ``out_vma`` call sites.

This module is parsed by the AST call-site audit
(:func:`qba_tpu.analysis.vma.check_spmd_call_sites`), never imported
for execution.  It reproduces both revert shapes of KI-1 inside a
shard_map-style body: a builder call that drops ``out_vma`` entirely
and one that hard-codes ``out_vma=None``.
"""

from qba_tpu.ops.round_kernel import build_round_step
from qba_tpu.ops.round_kernel_tiled import build_verdict_kernel


def shard_body(cfg, blk, n_local, interpret):
    step = build_round_step(cfg, interpret=interpret, n_recv=n_local)
    verdict = build_verdict_kernel(
        cfg, blk, interpret=interpret, n_recv=n_local, out_vma=None,
    )
    return step, verdict
