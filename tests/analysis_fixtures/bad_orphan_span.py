"""Seeded KI-12 violation: a fresh trace id minted mid-request.

``settle_with_fresh_trace`` plays a worker-side settle hook that,
instead of adopting the ``trace_id`` riding the claimed queue file,
mints a brand-new one for the result and the telemetry root span.
Everything recorded under the new id — the worker's compile/dispatch/
readback spans, the settle event — can never stitch back to the
intake that created the request: the spans become orphans and the
client-visible trace ends at "admit", dark from claim to settle.

The KI-12 mint-site audit must flag this call site: ``mint_trace_id``
is only legal at the registered request origins (the frontend's
``_intake``, the campaign's ``_stamp_trace``), and this function is
neither.
"""

from qba_tpu.obs.tracing import mint_trace_id


def settle_with_fresh_trace(payload: dict) -> dict:
    """KI-12 mint-site finding: re-mints instead of adopting."""
    # BUG: the request's own trace_id is sitting right there in the
    # payload; minting a new one orphans every span downstream.
    payload["trace_id"] = mint_trace_id()
    return payload
