"""Seeded KI-12 violation: an unregistered metric name at an emitter.

``count_retry`` increments ``qba_frontend_retries_total`` — a
plausible-looking name that is NOT a row of
:data:`qba_tpu.obs.metrics.METRICS`.  At runtime the registry would
raise; statically, the KI-12 metric-name audit must flag the call so
the fork of the one name table is caught before any process runs.
"""

from qba_tpu.obs.metrics import MetricsRegistry


def count_retry(reg: MetricsRegistry) -> None:
    """KI-12 metric-name finding: the name table has no such row."""
    reg.inc("qba_frontend_retries_total")
