"""Seeded KI-5 violation: step-1 generation leaked back out of the
one launch.

The round-11 contract is machine-checked, not asserted: when
``mega_gen`` resolves ``"gf2"`` the trial jaxpr must carry ZERO
host-side ``scan``s outside the single ``pallas_call`` (host
generation necessarily traces its two GF(2) measurement sweeps as
scans).  This fixture pairs a gf2-resolving config with the HOST-gen
trace of the same shape — exactly what a regressed dispatch would
produce if the gen-fused prologue silently fell back to the host
sampler while the resolver still claimed ``"gf2"``.  The
``mega-gen-in-kernel`` pin must flag it.
"""

import dataclasses

from qba_tpu.config import QBAConfig


def leaky_config() -> QBAConfig:
    """The headline stabilizer shape, forced gen-fused — a shape
    where the gf2 plan IS admitted, so the pin is armed."""
    return QBAConfig(
        n_parties=11, size_l=64, n_dishonest=3,
        qsim_path="stabilizer", mega_gen="gf2",
    )


def leaky_trace():
    """The megakernel trial jaxpr with generation ON THE HOST — the
    measurement sweeps ride as host-side scans next to the launch."""
    from qba_tpu.analysis.launches import _trace_trial

    cfg = dataclasses.replace(leaky_config(), mega_gen="host")
    return _trace_trial(cfg, "pallas_mega")
