"""Seeded KI-2 violation: an oversharded per-device budget.

A 257-party list size so large that even after tp-way receiver
sharding one device cannot hold a single trial's pool shard under the
v5e HBM model — the mesh shape is undersized for the mailbox pool and
dispatching it would OOM per device.  The sharded KI-2 pass must
predict that statically (``sharded-hbm`` finding), not leave it to the
first device allocation failure.
"""

from qba_tpu.config import QBAConfig

#: (dp, tp) mesh the fixture overshards against — matches the lint's
#: default mesh so ``check_memory`` flags it without extra wiring.
OVERSHARDED_MESH = (2, 4)


def oversharded_config() -> QBAConfig:
    """257 parties at size_l=16384: per-device pool shard > HBM."""
    return QBAConfig(n_parties=257, size_l=16384, n_dishonest=10)
