"""Seeded KI-10 violation: the pre-PR-12 reclaim double-execution race.

``serve_file_queue`` here claims with the atomic rename but NEVER
re-stamps the claim file's mtime — so claim staleness is measured from
the producer's *enqueue* time, exactly the shipped behavior before the
claim-instant ``os.utime`` fix.  A request that waited in the inbox
longer than the reclaim timeout looks stale the moment it is claimed:
a peer replica's reclaimer steals it from its live claimant, a second
worker claims and executes it concurrently, and the client can see two
results for one request id.

The KI-10 model checker extracts ``restamp_on_claim=False`` from this
function's AST, re-runs the same bounded scenarios, and must print the
minimal interleaving schedule (enqueue, age-in-inbox, claim, steal,
re-claim) that falsifies the single-executor and exactly-once-settle
invariants.

The shipped form is ``serve/transport.py``'s ``serve_file_queue``: the
same loop with ``os.utime(claimed, (claim_t, claim_t))`` right after
the claim rename (the ``# qba-protocol: restamp`` site).
"""

import os
import time


def serve_file_queue(server, paths, emit, decode_request_line, poll_s):
    """Pre-fix claim loop: rename-only claim, no mtime re-stamp."""
    claim_of = {}
    while True:
        names = sorted(
            n for n in os.listdir(paths["inbox"]) if n.endswith(".json")
        )
        for name in names:
            claimed = os.path.join(paths["claimed"], name)
            try:
                os.replace(os.path.join(paths["inbox"], name), claimed)
            except OSError:
                continue  # another consumer claimed it
            # BUG: no os.utime here — the claim file keeps the
            # producer's enqueue-time mtime, so inbox wait counts
            # toward claim staleness and a backlogged request is
            # reclaimable the instant it is claimed.
            with open(claimed) as f:
                req = decode_request_line(f.read())
            server.submit(req)
            claim_of[req.request_id] = name
            emit(server.pump())
        if os.path.exists(paths["stop"]):
            emit(server.flush())
            return claim_of
        if not names:
            time.sleep(poll_s)
