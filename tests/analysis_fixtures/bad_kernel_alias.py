"""Seeded KI-5 violations: a kernel missing ``input_output_aliases``
and an alias dict drifted out of sync with the operand layout.

* :func:`missing_alias_update` — an in-place-shaped update kernel
  (output exactly matches an input's shape+dtype) that declares *no*
  aliases: the output is a fresh HBM buffer the input could have
  carried.  This is the donation-miss the lint exists for — Pallas
  accepts it silently.
* :func:`tampered_alias_jaxpr` — Pallas rejects a shape/dtype-
  mismatched alias at trace time, so operand-layout drift (an operand
  inserted without renumbering the alias dict) is seeded post-trace by
  rewriting the equation params, exactly the artifact a stale lowering
  or hand-edited jaxpr would ship.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _update_kernel(x_ref, d_ref, o_ref):
    o_ref[...] = x_ref[...] + d_ref[...]


def missing_alias_update(pool, delta):
    """State-shaped kernel with no aliases: KI-5 donation-miss."""
    return pl.pallas_call(
        _update_kernel,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        interpret=True,
    )(pool, delta)


def donated_alias_update(pool, delta):
    """The shipped form: the state operand donates into the output."""
    return pl.pallas_call(
        _update_kernel,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases={0: 0},
        interpret=True,
    )(pool, delta)


def tampered_alias_jaxpr():
    """A traced aliased kernel whose alias dict is then renumbered to
    point at the (differently-shaped) delta operand — KI-5
    alias-consistency."""
    pool, delta = example_operands()
    delta = delta[:4]  # different shape than the pool

    def k(x_ref, d_ref, o_ref):
        o_ref[...] = x_ref[...]

    closed = jax.make_jaxpr(lambda p, d: pl.pallas_call(
        k,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases={0: 0},
        interpret=True,
    )(p, d))(pool, delta)
    for eqn in closed.jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            eqn.params["input_output_aliases"] = ((1, 0),)
    return closed


def example_operands():
    return (
        jnp.zeros((8, 128), jnp.float32),
        jnp.ones((8, 128), jnp.float32),
    )
