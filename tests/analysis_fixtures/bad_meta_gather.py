"""Seeded KI-3 violation: the round-4 meta-gather bug, reproduced.

The tiled rebuild kernel gathers rows of the packed pool meta
(``count``/``v``/``sent``/``cell`` columns, cell ids up to the pool
capacity) through a one-hot float matmul.  Shipped code passes
``precision=jax.lax.Precision.HIGHEST``; this fixture is the same
gather *without* it — on TPU the MXU would run it in bf16 passes and
any id above 256 silently rounds to even.
"""

import jax.numpy as jnp


def bad_meta_gather(onehot, meta):
    """Default-precision gather of int32 meta rows via a f32 one-hot."""
    return jnp.dot(onehot, meta.astype(jnp.float32)).astype(jnp.int32)


def good_meta_gather(onehot, meta):
    """The shipped form of the same gather (exact on the MXU)."""
    import jax

    return jnp.dot(
        onehot, meta.astype(jnp.float32),
        precision=jax.lax.Precision.HIGHEST,
    ).astype(jnp.int32)
