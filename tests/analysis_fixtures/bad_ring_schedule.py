"""Seeded KI-5 violation: the neighbor-ring hop schedule drifted.

``check_spmd_launches`` pins the party-sharded transport by counting
``ppermute`` hops in the traced device program: the ring trace must
carry exactly ``leaves x n_rounds x (tp - 1)`` hops — that counted
schedule is what closes the TPU in-kernel remote-DMA model for the
sharded megakernel (the hops it cannot trace off-TPU).  This fixture
wraps the spmd dispatch so a request for ``comms="ring"`` silently
runs the broadcast ``all_gather`` transport instead — zero hops where
the schedule demands a full ring — the exact regression (a transport
swap that nobody re-priced) the pin exists to catch.
"""


def silent_allgather_spmd_batch(real_spmd_batch):
    """Wrap ``_spmd_batch`` to ignore the requested transport and
    always gather by broadcast: the ring trace then carries 0
    ``ppermute`` hops and the schedule pin must fire."""

    def wrapped(cfg, mesh, keys, engine, check_vma, comms):
        return real_spmd_batch(
            cfg, mesh, keys, engine, check_vma, "all_gather"
        )

    return wrapped
