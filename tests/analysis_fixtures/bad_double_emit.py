"""Seeded KI-10 violation: a reclaimer that emits on every push-back.

``_reclaim_stale`` here writes a failure result to the outbox on EVERY
reclaim, not only on the terminal dead-letter branch.  The first time
a stale claim is pushed back to the inbox the client's future resolves
with that failure — and when the retry then succeeds, a second result
lands for the same request id: exactly-once settle is violated on
every successful crash recovery.

The KI-10 model checker extracts ``emit_only_at_dead_letter=False``
from this function's AST and must kill it with a minimal schedule in
which a reclaim's spurious emit races the retry's real one.

The shipped form is ``serve/transport.py``'s ``_reclaim_stale``: the
``emit([EvalResult.failure(...)])`` call lives only inside the
``k >= max_reclaims`` dead-letter branch (the ``# qba-protocol:
dead-letter`` site); an ordinary reclaim moves the file silently.
"""

import os
import time


def _reclaim_stale(paths, attempts, live, timeout_s, max_reclaims, emit, failure):
    """Bad reclaimer: every reclaim also resolves the client future."""
    reclaimed = 0
    now = time.time()
    names = sorted(
        n for n in os.listdir(paths["claimed"]) if n.endswith(".json")
    )
    for name in names:
        if name in live:
            continue
        path = os.path.join(paths["claimed"], name)
        age = now - os.path.getmtime(path)
        k = attempts.get(name, 0)
        if k >= max_reclaims:
            os.replace(path, os.path.join(paths["dead"], name))
            emit([failure(name, f"dead-lettered after {k} reclaims")])
            continue
        if age < timeout_s * (2 ** k):
            continue
        os.replace(path, os.path.join(paths["inbox"], name))
        # BUG: an ordinary push-back must be silent — the retry is
        # still in flight.  Emitting here resolves the client future
        # with a failure that the retry's real result then duplicates.
        emit([failure(name, f"reclaimed (attempt {k + 1})")])
        attempts[name] = k + 1
        reclaimed += 1
    return reclaimed
