"""Seeded-violation fixtures for the static invariant checker tests.

Each module reproduces one Known-Issue regression shape in isolation so
``tests/test_analysis.py`` can assert the lint actually fires on it —
the adversarial half of the clean-tree zero-findings contract.
"""
