"""Seeded KI-6 violation: an unfenced mid-pipeline host sync.

``drain_results`` reads device results back with a bare
``np.asarray`` between dispatches — an implicit device→host transfer
that blocks the host until the device drains, with no fenced span to
attribute the stall and nothing marking it intentional.  On the
double-buffered serve path this is exactly the bug that serializes
chunk k's compute against chunk k+1's dispatch.

``drain_results_fenced`` is the shipped discipline: the same readback
inside a telemetry span that sets ``fenced = True``.
"""

import numpy as np


def drain_results(dev_results, sink):
    """Unfenced mid-pipeline readback: KI-6 host-sync finding."""
    for res in dev_results:
        host = np.asarray(res)
        sink.append(host.sum())


def drain_results_fenced(dev_results, sink, recorder):
    """The shipped form: readback inside a fenced span."""
    for res in dev_results:
        with recorder.span("fixture.readback") as sp:
            sp.fenced = True
            host = np.asarray(res)
        sink.append(host.sum())
