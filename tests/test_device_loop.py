"""Device-resident sequential stopping (ROADMAP item 3, docs/STATS.md
"Device-resident stopping").

Five contracts:

* **Table exactness** — :func:`qba_tpu.stats.device.stop_tables` agrees
  with the host rule's own ``decision()`` at EVERY reachable
  ``(successes, chunks)`` total, by brute force; the width rule's
  unimodality (the structural assumption behind the two-ended table) is
  pinned per ``n``.
* **Stop-boundary bit-identity** — the triad: the host targeted loop,
  the device ``lax.while_loop``, and the fixed-budget run's prefix all
  execute bit-identical chunks and stop at the same chunk boundary, on
  every round engine and across shapes, strategies and noise.
* **Checkpoint interop** — a checkpoint written by either dispatch mode
  resumes under the other with identical chunks and stop decision.
* **KI-6 single-dispatch proof** — the shipped loop's traced jaxpr
  carries zero host callbacks/infeed/outfeed and exactly one
  ``while`` holding the engine program; the seeded bad fixture is
  flagged.
* **Serve parity** — a device-dispatch server returns EvalResults
  bit-identical to the host server's, and ineligible requests fall back
  to the host bucket path.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.obs.timers import PhaseTimers
from qba_tpu.stats import parse_target
from qba_tpu.stats.device import stop_tables
from qba_tpu.sweep import run_surface, run_sweep

DECIDE = "decide vs 1/3 @ 95%"


def _fires_host(target, k, n):
    """The host rule's own verdict at totals (k, n): fresh rule, one
    aggregate observation (both PR 10 rules are totals-pure)."""
    rule = target.make_rule()
    rule.observe(k, n)
    return rule.decision() is not None


def _triad(cfg, target, n_chunks, chunk_trials):
    """Host loop vs device loop vs fixed-budget prefix; returns
    (host, device) results after asserting the bit-identity bar."""
    host = run_sweep(
        cfg, n_chunks=n_chunks, chunk_trials=chunk_trials, target=target
    )
    dev = run_sweep(
        cfg,
        n_chunks=n_chunks,
        chunk_trials=chunk_trials,
        target=target,
        dispatch="device",
    )
    # Same executed chunks (ChunkResult equality ignores timings), same
    # stop boundary, same typed decision — including the anytime-valid
    # estimate surfaced at stop.
    assert dev.chunks == host.chunks
    assert dev.stop == host.stop
    assert dev.dispatch == "device" and host.dispatch == "host"
    # The fixed-budget run's prefix is the same trial data: stopping
    # early never changes what was computed, only how much.
    fixed = run_sweep(cfg, n_chunks=n_chunks, chunk_trials=chunk_trials)
    assert host.chunks == fixed.chunks[: len(host.chunks)]
    return host, dev


class TestStopTables:
    @pytest.mark.parametrize(
        "spec",
        [
            "decide vs 1/3 @ 95%",
            "decide vs 0.5 @ 99%",
            "ci_width<=0.28",
            "ci_width<=0.5",
        ],
    )
    def test_brute_force_equivalence(self, spec):
        # The defining property: at every reachable (K, N = i*ct) the
        # table fires iff the host rule's decision() fires.
        target = parse_target(spec)
        n_chunks, ct = 6, 7
        lo, hi = stop_tables(target, n_chunks, ct)
        for i in range(1, n_chunks + 1):
            n = i * ct
            for k in range(n + 1):
                table_fires = bool(k <= lo[i] or k >= hi[i])
                assert table_fires == _fires_host(target, k, n), (
                    spec,
                    i,
                    k,
                )

    def test_row_zero_is_sentinel(self):
        # Zero observations never fire: the device loop, like the host
        # loop, must run at least one chunk.
        lo, hi = stop_tables(parse_target(DECIDE), 4, 8)
        assert lo[0] == -1 and hi[0] == 1
        assert lo.dtype == np.int32 and hi.dtype == np.int32
        assert lo.shape == hi.shape == (5,)

    @pytest.mark.parametrize("n", [8, 16, 41])
    def test_width_unimodal_in_k(self, n):
        # The structural assumption behind the two-ended width table:
        # width_at(., n) rises to a single peak then falls — once the
        # sequence turns down it never turns back up.
        rule = parse_target("ci_width<=0.1").make_rule()
        w = [rule.width_at(k, n) for k in range(n + 1)]
        turned_down = False
        for a, b in zip(w, w[1:]):
            if b < a:
                turned_down = True
            elif b > a:
                assert not turned_down, (n, w)

    def test_validation(self):
        t = parse_target(DECIDE)
        with pytest.raises(ValueError, match="n_chunks"):
            stop_tables(t, 0, 8)
        with pytest.raises(ValueError, match="chunk_trials"):
            stop_tables(t, 4, 0)


class TestDeviceSweepTriad:
    @pytest.mark.parametrize(
        "engine,p,l,d,ct",
        [
            ("xla", 11, 64, 3, 8),
            ("pallas_fused", 11, 64, 3, 8),
            ("pallas_mega", 11, 64, 3, 8),
            ("xla", 17, 16, 4, 16),
            ("pallas_fused", 17, 16, 4, 16),
            ("pallas_mega", 17, 16, 4, 16),
        ],
    )
    def test_triad_engines(self, engine, p, l, d, ct):
        # ISSUE 15 acceptance: host loop, device loop, and fixed-budget
        # prefix stop at the same chunk boundary with bit-identical
        # chunks — at 11p/64 and 17p/16 on all three engines.
        cfg = QBAConfig(
            n_parties=p,
            size_l=l,
            n_dishonest=d,
            trials=ct,
            seed=5,
            round_engine=engine,
        )
        host, dev = _triad(cfg, DECIDE, 3, ct)
        assert host.stop is not None and dev.stop is not None
        assert dev.stop.reason == host.stop.reason

    def test_triad_split_strategy(self):
        cfg = QBAConfig(
            n_parties=5,
            size_l=8,
            n_dishonest=2,
            trials=8,
            seed=9,
            strategy="split",
        )
        _triad(cfg, DECIDE, 4, 8)

    def test_triad_noise_point(self):
        cfg = QBAConfig(
            n_parties=5,
            size_l=16,
            n_dishonest=1,
            trials=8,
            seed=2,
            p_depolarize=0.05,
            p_measure_flip=0.02,
        )
        _triad(cfg, DECIDE, 4, 8)

    def test_budget_exhausted_parity(self):
        # A target no small budget can resolve: both loops run the
        # whole budget and surface the same typed exhaustion.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8, seed=4)
        host, dev = _triad(cfg, "ci_width<=0.05", 2, 8)
        assert host.stop.reason == "budget_exhausted"
        assert dev.stop.reason == "budget_exhausted"
        assert len(dev.chunks) == 2

    def test_decision_on_final_budget_chunk_is_not_divergence(self):
        # split @ seed 9 fires exactly at the last budget chunk: the
        # loop exits on i == n_chunks either way, so the divergence
        # check must stay quiet (it warned spuriously once).
        import warnings

        cfg = QBAConfig(
            n_parties=5,
            size_l=8,
            n_dishonest=2,
            trials=8,
            seed=9,
            strategy="split",
        )
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            res = run_sweep(
                cfg, n_chunks=4, chunk_trials=8, target=DECIDE,
                dispatch="device",
            )
        assert len(res.chunks) == 4
        assert res.stop.reason in ("decided_above", "decided_below")

    def test_device_loop_is_one_fenced_span(self):
        # Satellite: loop-level telemetry replaces the per-chunk
        # dispatch/readback spans — a device run records ONE fenced
        # device_loop span and zero per-chunk phases.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8, seed=4)
        timers = PhaseTimers()
        res = run_sweep(
            cfg,
            n_chunks=4,
            chunk_trials=8,
            target=DECIDE,
            dispatch="device",
            timers=timers,
        )
        assert timers.total("device_loop") > 0.0
        assert timers.total("dispatch") == 0.0
        assert timers.total("readback") == 0.0
        assert res.stats_summary()["dispatch"] == "device"

    def test_device_dispatch_validation(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8)
        with pytest.raises(ValueError, match="needs a target"):
            run_sweep(cfg, n_chunks=2, dispatch="device")
        with pytest.raises(ValueError, match="custom runner"):
            run_sweep(
                cfg,
                n_chunks=2,
                target=DECIDE,
                dispatch="device",
                runner=lambda cfg, keys: None,
            )
        with pytest.raises(ValueError, match="dispatch must be"):
            run_sweep(cfg, n_chunks=2, target=DECIDE, dispatch="tpu")


class TestDeviceCheckpoint:
    CFG = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8, seed=11)

    def test_device_checkpoint_resumes_on_host(self, tmp_path):
        ckpt = str(tmp_path / "sweep.json")
        dev = run_sweep(
            self.CFG,
            n_chunks=6,
            chunk_trials=8,
            target=DECIDE,
            dispatch="device",
            checkpoint=ckpt,
        )
        payload = json.loads(open(ckpt).read())
        assert payload["stats"]["dispatch"] == "device"
        host = run_sweep(
            self.CFG,
            n_chunks=6,
            chunk_trials=8,
            target=DECIDE,
            checkpoint=ckpt,
        )
        assert host.resumed_chunks == len(dev.chunks)
        assert host.chunks == dev.chunks
        assert host.stop == dev.stop

    def test_host_partial_checkpoint_resumes_on_device(self, tmp_path):
        ckpt = str(tmp_path / "sweep.json")
        # A budget too small to resolve leaves a partial prefix behind.
        partial = run_sweep(
            self.CFG,
            n_chunks=1,
            chunk_trials=8,
            target="ci_width<=0.05",
            checkpoint=ckpt,
        )
        assert partial.stop.reason == "budget_exhausted"
        dev = run_sweep(
            self.CFG,
            n_chunks=4,
            chunk_trials=8,
            target=DECIDE,
            dispatch="device",
            checkpoint=ckpt,
        )
        assert dev.resumed_chunks == 1
        fresh = run_sweep(
            self.CFG, n_chunks=4, chunk_trials=8, target=DECIDE,
            dispatch="device",
        )
        assert dev.chunks == fresh.chunks
        assert dev.stop == fresh.stop


class TestDeviceSurface:
    def test_surface_parity_vs_host_allocator(self, tmp_path):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8, seed=3)
        kw = dict(
            strategies=("reference",),
            noise_points=[(0.0, 0.0)],
            size_ls=[8, 16],
            chunk_trials=8,
            target=DECIDE,
            budget_chunks=8,
        )
        host = run_surface(cfg, **kw)
        dev = run_surface(cfg, dispatch="device", **kw)
        assert len(host) == len(dev) == 2
        for hc, dc in zip(host, dev):
            # Per-cell chunk contents and stop decisions are exact
            # (schedule ORDER may differ — f32 width tiering on device);
            # with a budget that resolves every cell, the per-cell work
            # is identical.
            assert dc.result.chunks == hc.result.chunks
            assert dc.result.stop == hc.result.stop
            assert dc.result.dispatch == "device"
            assert dc.manifest["stats"]["dispatch"] == "device"
            alloc = dc.manifest["stats"]["allocator"]
            assert alloc["dispatch"] == "device"
            assert alloc["spent_chunks"] == (
                hc.manifest["stats"]["allocator"]["spent_chunks"]
            )

    def test_device_surface_validation(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=8)
        with pytest.raises(ValueError, match="needs a target"):
            run_surface(
                cfg,
                strategies=("reference",),
                noise_points=[(0.0, 0.0)],
                size_ls=[16],
                dispatch="device",
            )


class TestDeviceLoopLint:
    def test_shipped_loop_proven_clean(self):
        from qba_tpu.analysis.transfers import check_device_loop

        rep = check_device_loop()
        assert rep.ok, [f.message for f in rep.findings]
        assert any("PROVEN eliminated" in n for n in rep.notes)
        assert rep.stats["device_loop_obligations"] == 3

    def test_leaky_fixture_flagged(self):
        from qba_tpu.analysis.transfers import audit_device_loop
        from tests.analysis_fixtures import bad_device_loop as bdl

        n, ct = 4, 8
        lo = jnp.full(n + 1, -1, jnp.int32)
        hi = jnp.full(n + 1, n * ct + 1, jnp.int32)
        closed = jax.make_jaxpr(
            lambda lo_, hi_: bdl.leaky_loop(0, n, ct, lo_, hi_)
        )(lo, hi)
        rep = audit_device_loop(closed, "fixture/leaky_loop")
        assert not rep.ok
        assert any(
            "host round trip per loop iteration" in f.message
            for f in rep.findings
        )

    def test_clean_fixture_passes(self):
        from qba_tpu.analysis.transfers import audit_device_loop
        from tests.analysis_fixtures import bad_device_loop as bdl

        n, ct = 4, 8
        lo = jnp.full(n + 1, -1, jnp.int32)
        hi = jnp.full(n + 1, n * ct + 1, jnp.int32)
        closed = jax.make_jaxpr(
            lambda lo_, hi_: bdl.clean_loop(0, n, ct, lo_, hi_)
        )(lo, hi)
        rep = audit_device_loop(closed, "fixture/clean_loop")
        assert rep.ok, [f.message for f in rep.findings]


class TestServeDevice:
    @staticmethod
    def _run(dispatch, target, trials=256, ct=16, seed=3):
        from qba_tpu.serve.engine import QBAServer, serve_batch
        from qba_tpu.serve.request import EvalRequest

        srv = QBAServer(chunk_trials=ct, dispatch=dispatch)
        req = EvalRequest(
            request_id="r1",
            n_parties=5,
            size_l=16,
            n_dishonest=1,
            trials=trials,
            seed=seed,
            round_engine="xla",
            strategy="collude",
            target=target,
        )
        (res,) = serve_batch(srv, [req])
        assert res.error is None, res.error
        return res, srv

    @pytest.mark.parametrize("tgt", [DECIDE, "ci_width<=0.3"])
    def test_parity_with_host_server(self, tgt):
        h, _ = self._run("host", tgt)
        d, srv = self._run("device", tgt)
        assert d.n_trials == h.n_trials
        assert d.successes == h.successes
        assert d.success == h.success  # per-trial bits, bit-identical
        assert d.stop == h.stop
        assert d.ci == h.ci
        assert d.chunks == h.chunks
        assert d.manifest["stats"]["dispatch"] == "device"
        assert srv.stats()["dispatch"] == "device"

    def test_untargeted_request_falls_back_to_host_path(self):
        from qba_tpu.serve.engine import QBAServer, serve_batch
        from qba_tpu.serve.request import EvalRequest

        srv = QBAServer(chunk_trials=16, dispatch="device")
        req = EvalRequest(
            request_id="u1",
            n_parties=5,
            size_l=16,
            n_dishonest=1,
            trials=32,
            seed=7,
            round_engine="xla",
        )
        (res,) = serve_batch(srv, [req])
        assert res.error is None and res.n_trials == 32
        assert "dispatch" not in (res.manifest["stats"] or {})

    def test_return_decisions_falls_back_to_host_path(self):
        from qba_tpu.serve.engine import QBAServer, serve_batch
        from qba_tpu.serve.request import EvalRequest

        srv = QBAServer(chunk_trials=16, dispatch="device")
        req = EvalRequest(
            request_id="d1",
            n_parties=5,
            size_l=16,
            n_dishonest=1,
            trials=64,
            seed=7,
            round_engine="xla",
            target=DECIDE,
            return_decisions=True,
        )
        (res,) = serve_batch(srv, [req])
        assert res.error is None and res.decisions is not None

    def test_dispatch_validation(self):
        from qba_tpu.serve.engine import QBAServer

        with pytest.raises(ValueError, match="dispatch"):
            QBAServer(dispatch="tpu")


class TestCarryBytes:
    def test_device_loop_carry_accounting(self):
        from qba_tpu.analysis.memory import device_loop_carry_bytes

        base = device_loop_carry_bytes(64, 512)
        assert base["total_bytes"] == (
            base["per_cell_bytes"] + base["shared_bytes"]
        )
        # Per-trial success bits (the serve prefix loop) add exactly
        # one bool per trial plus the 8-byte key rows.
        serve = device_loop_carry_bytes(64, 512, per_trial_bits=True)
        assert (
            serve["total_bytes"] - base["total_bytes"] == 64 * 512 * (1 + 8)
        )
        # More cells scale the per-cell block and add the schedule logs.
        multi = device_loop_carry_bytes(64, 512, n_cells=4)
        assert multi["total_bytes"] > 4 * base["per_cell_bytes"]
