"""Effects lint (``qba-tpu lint --effects``): KI-5 donation/aliasing,
KI-6 host-sync discipline, and the sharded KI-2 per-device budgets.

Same contract as ``tests/test_analysis.py``: the passes must be
silent on the shipped tree and loud on every seeded regression in
``tests/analysis_fixtures/``.
"""

import io
import os
import textwrap

import jax
import pytest

from qba_tpu.analysis.driver import run_lint
from qba_tpu.analysis.effects import (
    DONATE_ALLOW_MARKER,
    annotation_at,
    audit_pallas_calls,
    audit_scans,
    check_effects,
    check_jit_donation,
)
from qba_tpu.analysis.findings import Report
from qba_tpu.analysis.memory import (
    NORTH_STAR_CEILING_BAND,
    check_memory,
    sharded_trial_ceiling,
    trial_ceiling,
)
from qba_tpu.analysis.transfers import (
    SYNC_ALLOW_MARKER,
    audit_module,
    check_serve_dispatch,
    check_transfers,
)
from qba_tpu.config import QBAConfig

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

#: The lint matrix's cheap point (every engine live, fused plan
#: resolves, even lieutenant count) — see tests/test_analysis.py.
CHEAP = QBAConfig(17, 16, 4)


def _sync_stats():
    return {
        "sync_sites_checked": 0,
        "sync_sites_fenced": 0,
        "sync_sites_allowlisted": 0,
    }


# ---------------------------------------------------------------------------
# Clean tree: the shipped kernels and modules are donation- and
# sync-clean by construction.


@pytest.mark.slow
def test_clean_tree_effects_zero_findings():
    report = run_lint(configs=[("cheap", CHEAP)], effects=True)
    assert report.ok, report.render()
    # The audits actually bit: kernels audited, carries chased,
    # sync sites resolved.
    assert report.stats["pallas_calls_audited"] > 0
    assert report.stats["alias_pairs_checked"] > 0
    assert report.stats["kernel_scans_audited"] > 0
    assert report.stats["donated_carries"] > 0
    assert report.stats["sync_sites_checked"] > 0
    assert report.stats["jits_audited"] > 0


def test_clean_tree_transfers_zero_findings():
    report = check_transfers()
    assert report.ok, report.render()
    # The hot tree resolves every sync site explicitly: the serve
    # readback + sweep readback are fenced, the intake key derivation
    # and wire decode are allowlisted with citations.
    assert report.stats["sync_sites_fenced"] >= 2
    assert report.stats["sync_sites_allowlisted"] >= 2
    assert report.stats["dispatch_proof_obligations"] == 4


def test_clean_tree_jit_donation_policy():
    report = check_jit_donation()
    assert report.ok, report.render()
    assert report.stats["jits_audited"] > 0
    # The zero-donation policy on dispatch jits is recorded, so a
    # future donate_argnums claim is a conscious change.
    assert any("zero donate_argnums" in n for n in report.notes)


# ---------------------------------------------------------------------------
# KI-5 fixtures: undonated scan carry, missing/tampered aliases.


def test_fixture_undonated_scan_carry():
    from tests.analysis_fixtures import bad_scan_carry as bsc

    pool = bsc.example_pool()
    report = audit_scans(jax.make_jaxpr(bsc.undonated_round_loop)(pool))
    assert not report.ok
    assert {(f.ki, f.check) for f in report.findings} == {
        ("KI-5", "scan-carry")
    }
    assert report.stats["donated_carries"] == 0

    report = audit_scans(jax.make_jaxpr(bsc.donated_round_loop)(pool))
    assert report.ok, report.render()
    assert report.stats["donated_carries"] == 1


def test_fixture_missing_kernel_alias():
    from tests.analysis_fixtures import bad_kernel_alias as bka

    p, d = bka.example_operands()
    report = audit_pallas_calls(
        jax.make_jaxpr(bka.missing_alias_update)(p, d)
    )
    assert [(f.ki, f.check) for f in report.findings] == [
        ("KI-5", "donation-miss")
    ]
    # The finding carries the fixture's call site so the annotation
    # escape hatch is actionable.
    assert "bad_kernel_alias.py" in report.findings[0].where
    assert DONATE_ALLOW_MARKER in report.findings[0].message

    report = audit_pallas_calls(
        jax.make_jaxpr(bka.donated_alias_update)(p, d)
    )
    assert report.ok, report.render()


def test_fixture_tampered_alias_inconsistent():
    from tests.analysis_fixtures import bad_kernel_alias as bka

    report = audit_pallas_calls(bka.tampered_alias_jaxpr())
    assert [(f.ki, f.check) for f in report.findings] == [
        ("KI-5", "alias-consistency")
    ]


def test_donate_allow_marker_demotes(tmp_path):
    """An annotated donation miss becomes a note, not a finding —
    and the justification text survives into the note."""
    from tests.analysis_fixtures import bad_kernel_alias as bka

    p, d = bka.example_operands()
    closed = jax.make_jaxpr(bka.missing_alias_update)(p, d)
    # The finding anchors at the fixture's pallas_call line; verify
    # annotation_at's window against a copy we annotate ourselves.
    report = audit_pallas_calls(closed)
    where = report.findings[0].where
    path, _, line = where.rpartition(":")
    src = open(path).readlines()
    src.insert(int(line) - 1, f"    # {DONATE_ALLOW_MARKER} (test)\n")
    marked = tmp_path / "marked.py"
    marked.write_text("".join(src))
    assert annotation_at(
        f"{marked}:{int(line) + 1}", DONATE_ALLOW_MARKER
    ) == "(test)"


# ---------------------------------------------------------------------------
# KI-6 fixtures: unfenced mid-pipeline sync, dispatch-order drift.


def test_fixture_unfenced_sync():
    report = Report()
    stats = _sync_stats()
    audit_module(
        os.path.join(FIXTURES, "bad_unfenced_sync.py"), report, stats
    )
    assert [(f.ki, f.check) for f in report.findings] == [
        ("KI-6", "host-sync")
    ]
    assert SYNC_ALLOW_MARKER in report.findings[0].message
    # The fenced twin of the same readback is recognized, not flagged.
    assert stats == {
        "sync_sites_checked": 2,
        "sync_sites_fenced": 1,
        "sync_sites_allowlisted": 0,
    }


def test_sync_allow_marker_demotes(tmp_path):
    src = textwrap.dedent("""\
        import numpy as np

        def decode(payload):
            # qba-lint: sync-ok (host-side wire decode)
            return np.asarray(payload)
    """)
    mod = tmp_path / "annotated.py"
    mod.write_text(src)
    report = Report()
    stats = _sync_stats()
    audit_module(str(mod), report, stats)
    assert report.ok, report.render()
    assert stats["sync_sites_allowlisted"] == 1
    assert any("wire decode" in n for n in report.notes)


def test_serve_dispatch_proof_clean():
    report = check_serve_dispatch()
    assert report.ok, report.render()


def test_serve_dispatch_proof_flags_reordered(tmp_path):
    """A _dispatch that drains (and syncs) before enqueuing the new
    chunk — the double-buffer-serializing regression — is flagged."""
    src = textwrap.dedent("""\
        import numpy as np

        class QBAServer:
            def _dispatch(self, chunk):
                while len(self._in_flight) > self.depth - 1:
                    self._drain_one()
                res = np.asarray(chunk.result)
                self._in_flight.append((chunk, res))

            def _drain_one(self):
                return self._in_flight.pop()
    """)
    mod = tmp_path / "engine.py"
    mod.write_text(src)
    report = check_serve_dispatch(str(mod))
    checks = [(f.ki, f.check) for f in report.findings]
    assert checks.count(("KI-6", "dispatch-order")) >= 2
    msgs = " ".join(f.message for f in report.findings)
    assert "before enqueuing" in msgs  # drain/sync precede append
    assert "pop(0)" in msgs  # non-FIFO drain


# ---------------------------------------------------------------------------
# Sharded KI-2: per-device budgets.


def test_sharded_ceiling_reduces_to_single_chip():
    ns = QBAConfig(33, 64, 10)
    for comms in ("ring", "all_gather"):
        sc = sharded_trial_ceiling(ns, dp=1, tp=1, comms=comms)
        assert sc["comms_buffer_bytes"] == 0
        assert sc["per_device_trials"] == trial_ceiling(ns)
        assert sc["mesh_trials"] == trial_ceiling(ns)


def test_sharded_north_star_budgets():
    """Pins the bands: the measured single-chip north-star band and
    the sharded per-device predictions derived from it, for both
    comms transports (the ring's constant-multiplier footprint is THE
    round-9 KI-2 claim — at tp=8 it more than doubles the all_gather
    ceiling)."""
    ns = QBAConfig(33, 64, 10)
    lo, hi = NORTH_STAR_CEILING_BAND
    assert lo <= trial_ceiling(ns) <= hi
    sc = sharded_trial_ceiling(ns, dp=2, tp=4)
    assert sc["comms"] == "ring"
    assert sc["n_recv"] == 8
    assert sc["per_device_pool_bytes"] == 2228224
    assert sc["comms_buffer_bytes"] == 2 * 2228224
    assert sc["per_device_trials"] == 1961
    assert sc["mesh_trials"] == 3922
    ag = sharded_trial_ceiling(ns, dp=2, tp=4, comms="all_gather")
    assert ag["comms_buffer_bytes"] == 3 * 2228224
    assert ag["per_device_trials"] == 1525
    # Full-width shard of this container's 8 devices.
    r8 = sharded_trial_ceiling(ns, dp=1, tp=8)
    ag8 = sharded_trial_ceiling(ns, dp=1, tp=8, comms="all_gather")
    assert r8["per_device_trials"] == 3923
    assert ag8["per_device_trials"] == 1615


def test_sharded_ring_ceiling_scales_linearly():
    """Acceptance pin: above the comms floor (tp >= 3, where the
    ring's resident slot pair saturates at 2 shards) the per-device
    ceiling under the ring model scales ~linearly in tp — doubling tp
    doubles trials/device within 10%."""
    ns = QBAConfig(33, 64, 10)
    c4 = sharded_trial_ceiling(ns, tp=4)["per_device_trials"]
    c8 = sharded_trial_ceiling(ns, tp=8)["per_device_trials"]
    assert abs(c8 / c4 - 2.0) <= 0.2
    # all_gather does NOT scale: its transient grows with tp.
    a4 = sharded_trial_ceiling(ns, tp=4, comms="all_gather")
    a8 = sharded_trial_ceiling(ns, tp=8, comms="all_gather")
    assert a8["per_device_trials"] / a4["per_device_trials"] < 1.5


def test_sharded_budget_notes_emitted():
    report = check_memory(CHEAP)
    assert report.ok, report.render()
    assert report.stats["sharded_meshes_checked"] == 2
    assert any("sharded-hbm[dp=2,tp=4]" in n for n in report.notes)
    assert any("sharded-hbm[dp=1,tp=8]" in n for n in report.notes)
    # Every sharded note carries the all_gather counterfactual.
    assert all("all_gather comms would cap" in n
               for n in report.notes if "sharded-hbm[" in n)
    # The per-device plan audit ran at the tp=4 and tp=8 shards.
    assert any(n.startswith("spmd[tp=4]/") for n in report.notes)
    assert any(n.startswith("spmd[tp=8]/") for n in report.notes)


def test_sharded_mesh_skip_note_when_indivisible():
    # f32-gdt point: n_lieutenants=10, tp=4 does not divide — a note,
    # never a finding (the mesh simply does not apply to the shape).
    report = check_memory(QBAConfig(11, 16, 3))
    assert report.ok, report.render()
    assert report.stats["sharded_meshes_checked"] == 0
    assert any("skipped" in n and "tp does not divide" in n
               for n in report.notes)


def test_fixture_oversharded_budget():
    from tests.analysis_fixtures import bad_sharded_budget as bsb

    cfg = bsb.oversharded_config()
    sc = sharded_trial_ceiling(cfg, *bsb.OVERSHARDED_MESH)
    assert sc["per_device_trials"] < 1
    report = check_memory(cfg)
    assert ("KI-2", "sharded-hbm") in {
        (f.ki, f.check) for f in report.findings
    }


# ---------------------------------------------------------------------------
# KI-5 round-11 fixtures: generation leaking back out of the one
# launch, and a drifted neighbor-ring hop schedule.


def test_fixture_mega_gen_leak_flagged():
    from qba_tpu.analysis.launches import _pin_mega_gen_in_kernel
    from tests.analysis_fixtures import bad_mega_gen_leak as bgl

    report = Report()
    _pin_mega_gen_in_kernel(bgl.leaky_config(), bgl.leaky_trace(), report)
    assert ("KI-5", "mega-gen-in-kernel") in {
        (f.ki, f.check) for f in report.findings
    }
    assert report.stats["mega_gen_host_scans"] > 0


def test_fixture_ring_schedule_drift_flagged(monkeypatch):
    import qba_tpu.parallel.spmd as spmd_mod
    from qba_tpu.analysis.launches import check_spmd_launches
    from tests.analysis_fixtures import bad_ring_schedule as brs

    monkeypatch.setattr(
        spmd_mod, "_spmd_batch",
        brs.silent_allgather_spmd_batch(spmd_mod._spmd_batch),
    )
    cfg = QBAConfig(
        n_parties=9, size_l=16, n_dishonest=2,
        round_engine="pallas_mega",
    )
    report = check_spmd_launches(cfg, {"pallas_mega"}, tp=2)
    assert ("KI-5", "spmd-launches") in {
        (f.ki, f.check) for f in report.findings
    }
    assert any("ring schedule" in f.message for f in report.findings)


# ---------------------------------------------------------------------------
# Per-config entry + CLI.


def test_check_effects_cheap_clean():
    from qba_tpu.analysis.traces import trace_paths

    paths, _ = trace_paths(CHEAP, {"pallas_tiled"})
    report = check_effects(CHEAP, paths, {"pallas_tiled"})
    assert report.ok, report.render()
    assert report.stats["pallas_calls_audited"] > 0
    assert report.stats["donated_carries"] > 0


@pytest.mark.slow
def test_cli_lint_effects_clean(tmp_path):
    import json

    from qba_tpu.cli import main

    out = io.StringIO()
    findings_json = tmp_path / "findings.json"
    rc = main(
        [
            "lint", "--effects", "--config", "17,16,4",
            "--findings-json", str(findings_json), "-v",
        ],
        out=out,
    )
    text = out.getvalue()
    assert rc == 0, text
    assert "0 finding(s)" in text
    payload = json.loads(findings_json.read_text())
    assert payload["schema"] == "qba-tpu/lint-findings/v1"
    assert payload["ok"] is True
    assert payload["effects"] is True
    assert payload["findings"] == []
    assert payload["stats"]["sync_sites_checked"] > 0
