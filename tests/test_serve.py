"""Serving-subsystem tests (docs/SERVING.md).

Five contracts:

* **Bucketing determinism** — chunk assembly is a pure function of the
  enqueue order: same request stream, same chunks (buckets, segments,
  key rows), and bucket identity ignores seed/trials but not shape or
  engine knobs.
* **Bit-identity** — a served result equals a direct
  :func:`~qba_tpu.backends.jax_backend.run_trials` run of the same
  config trial for trial (success AND decisions), on both the xla and
  pallas_fused engines, even when the request's trials are split
  across chunks and interleaved with other buckets.
* **Double-buffer ordering** — with depth-2 dispatch and interleaved
  buckets, every result lands under its own request id with its own
  seed's outputs.
* **Warm start** — a second server boot against the same cache dir
  restores the saved plans and serves the same shapes with ZERO
  resolver misses and ZERO compile probes (``PROBE_STATS``).
* **LRU bound** — the resolver memo respects its cap and counts
  evictions (long-lived mixed-shape processes must not grow without
  bound).
"""

import dataclasses
import json

import numpy as np
import pytest

from qba_tpu.backends.jax_backend import run_trials, trial_keys
from qba_tpu.config import QBAConfig
from qba_tpu.obs.manifest import validate_manifest
from qba_tpu.obs.telemetry import span_latency_summary
from qba_tpu.ops.round_kernel_tiled import (
    PROBE_STATS,
    clear_resolve_caches,
    resolve_cache_info,
    set_resolve_cache_cap,
)
from qba_tpu.serve import (
    EvalRequest,
    EvalResult,
    QBAServer,
    bucket_config,
    serve_batch,
)
from qba_tpu.serve.persist import save_plans, saved_configs
from qba_tpu.serve.scheduler import BucketScheduler


def _req(rid, n=4, L=8, d=1, trials=4, seed=0, engine="auto", **kw):
    return EvalRequest(
        request_id=rid, n_parties=n, size_l=L, n_dishonest=d,
        trials=trials, seed=seed, round_engine=engine, **kw,
    )


def _mixed_stream():
    """Three shape buckets, seeds/trials varied, interleaved arrival."""
    return [
        _req("a0", n=4, L=8, d=1, trials=5, seed=3),
        _req("b0", n=5, L=8, d=1, trials=6, seed=7),
        _req("c0", n=4, L=16, d=2, trials=4, seed=1),
        _req("a1", n=4, L=8, d=1, trials=11, seed=9),
        _req("b1", n=5, L=8, d=1, trials=3, seed=2),
        _req("a2", n=4, L=8, d=1, trials=2, seed=5),
    ]


# ---- bucketing ---------------------------------------------------------


def test_bucket_config_ignores_seed_and_trials_only():
    a = QBAConfig(5, 8, 1, trials=7, seed=42)
    b = QBAConfig(5, 8, 1, trials=900, seed=0)
    assert bucket_config(a, 64) == bucket_config(b, 64)
    # Shape and engine knobs DO split buckets.
    c = QBAConfig(5, 8, 1, trials=7, seed=42, round_engine="xla")
    assert bucket_config(a, 64) != bucket_config(c, 64)
    d = QBAConfig(5, 16, 1, trials=7, seed=42)
    assert bucket_config(a, 64) != bucket_config(d, 64)


def _assemble(stream, chunk_trials=8):
    """Run the scheduler alone (no jax) over a request stream."""
    sched = BucketScheduler(chunk_trials)
    rng = np.random.default_rng(0)
    chunks = []
    for req in stream:
        cfg = req.config()
        key_data = rng.integers(0, 2**32, size=(cfg.trials, 2), dtype=np.uint32)
        sched.enqueue(req.request_id, cfg, key_data)
    while True:
        chunk = sched.next_chunk()
        if chunk is None:
            break
        chunks.append(chunk)
    return chunks


def test_chunk_assembly_deterministic_and_complete():
    chunks_a = _assemble(_mixed_stream())
    chunks_b = _assemble(_mixed_stream())
    assert len(chunks_a) == len(chunks_b)
    for ca, cb in zip(chunks_a, chunks_b):
        assert ca.bucket == cb.bucket
        assert ca.segments == cb.segments
        assert np.array_equal(ca.key_data, cb.key_data)
    # Every request's trials are covered exactly once, in order.
    seen: dict[str, int] = {}
    for chunk in chunks_a:
        for seg in chunk.segments:
            assert seg.req_start == seen.get(seg.request_id, 0)
            seen[seg.request_id] = seg.req_start + seg.length
    assert seen == {r.request_id: r.trials for r in _mixed_stream()}
    # FIFO fairness: the first chunk serves the oldest request's bucket.
    assert chunks_a[0].segments[0].request_id == "a0"


def test_scheduler_rejects_bad_key_shape():
    sched = BucketScheduler(8)
    cfg = QBAConfig(4, 8, 1, trials=4)
    with pytest.raises(ValueError, match="key_data shape"):
        sched.enqueue("x", cfg, np.zeros((3, 2), dtype=np.uint32))


# ---- served results ----------------------------------------------------


@pytest.mark.parametrize("engine", ["xla", "pallas_fused"])
def test_served_results_bit_identical_to_direct_runs(engine):
    # chunk_trials=4 forces multi-chunk requests and interleaving with
    # the second bucket — the served slices must still reassemble to
    # exactly the direct run's per-trial outputs.
    server = QBAServer(chunk_trials=4)
    stream = [
        _req("s0", n=4, L=8, d=1, trials=6, seed=3, engine=engine,
             return_decisions=True),
        _req("s1", n=5, L=8, d=1, trials=5, seed=8, engine=engine,
             return_decisions=True),
        _req("s2", n=4, L=8, d=1, trials=3, seed=13, engine=engine,
             return_decisions=True),
    ]
    results = {r.request_id: r for r in serve_batch(server, stream)}
    assert set(results) == {r.request_id for r in stream}
    for req in stream:
        cfg = req.config()
        direct = run_trials(cfg, trial_keys(cfg))
        served = results[req.request_id]
        assert served.error is None
        assert served.success == [
            bool(x) for x in np.asarray(direct.trials.success)
        ]
        assert np.array_equal(
            np.asarray(served.decisions),
            np.asarray(direct.trials.decisions),
        ), req.request_id
        assert served.success_rate == pytest.approx(
            float(direct.success_rate)
        )


def test_double_buffer_ordering_and_manifests():
    # Depth-2 double buffering, requests split across chunks and
    # buckets interleaved: results must land under the right ids, and
    # every request carries a schema-valid manifest + its own span tree.
    server = QBAServer(chunk_trials=4, depth=2)
    results = serve_batch(server, _mixed_stream())
    by_id = {r.request_id: r for r in results}
    assert set(by_id) == {r.request_id for r in _mixed_stream()}
    for req in _mixed_stream():
        res = by_id[req.request_id]
        assert res.error is None
        assert res.n_trials == req.trials
        assert len(res.success) == req.trials
        direct = run_trials(req.config(), trial_keys(req.config()))
        assert res.success == [
            bool(x) for x in np.asarray(direct.trials.success)
        ], req.request_id
        validate_manifest(res.manifest)
        assert res.manifest["request_id"] == req.request_id
        assert res.manifest["config"]["seed"] == req.seed
        assert res.latency_s > 0
    # Multi-chunk request really did span chunks.
    assert by_id["a1"].chunks >= 2
    # The latency summary is computed from the request spans themselves.
    summary = server.latency_summary()
    assert summary["count"] == len(_mixed_stream())
    assert summary["p99_s"] >= summary["p50_s"] >= 0
    # Server-side chunk spans: readbacks are fenced, dispatches are not.
    readbacks = [s for s in server.recorder.spans if s.name == "serve.readback"]
    dispatches = [s for s in server.recorder.spans if s.name == "serve.dispatch"]
    assert readbacks and all(s.fenced for s in readbacks)
    assert dispatches and not any(s.fenced for s in dispatches)
    assert len(readbacks) == len(dispatches)


def test_bad_request_becomes_error_result_not_crash():
    server = QBAServer(chunk_trials=4)
    results = serve_batch(
        server,
        [_req("ok", trials=2), _req("bad", n=1, trials=1), _req("ok2", trials=2)],
    )
    by_id = {r.request_id: r for r in results}
    assert by_id["bad"].error and "n_parties" in by_id["bad"].error
    assert by_id["ok"].error is None and by_id["ok2"].error is None


def test_request_json_round_trip_and_unknown_field():
    req = _req("rt", trials=3, seed=5, engine="pallas_tiled")
    assert EvalRequest.from_json(req.to_json()) == req
    with pytest.raises(ValueError, match="unknown request field"):
        EvalRequest.from_json({"request_id": "x", "n_partyes": 4, "size_l": 8})
    res = EvalResult.failure("x", "boom")
    round_tripped = EvalResult.from_json(json.loads(json.dumps(res.to_json())))
    assert round_tripped.request_id == "x" and round_tripped.error == "boom"


def test_fingerprint_matches_manifest_config():
    req = _req("fp", trials=3, seed=5)
    server = QBAServer(chunk_trials=4)
    [res] = serve_batch(server, [req])
    assert res.manifest["config"] == req.fingerprint()


# ---- warm start --------------------------------------------------------


def test_warm_start_second_boot_zero_probes(tmp_path):
    cache_dir = str(tmp_path / "cache")
    stream = [
        _req("w0", n=4, L=8, d=1, trials=4, seed=3, engine="pallas_fused"),
        _req("w1", n=5, L=8, d=1, trials=4, seed=5, engine="pallas_tiled"),
        _req("w2", n=4, L=16, d=1, trials=4, seed=7, engine="xla"),
    ]
    clear_resolve_caches()
    try:
        s1 = QBAServer(chunk_trials=8, cache_dir=cache_dir)
        r1 = serve_batch(s1, stream)
        assert s1.restored_plans == 0
        first_misses = PROBE_STATS["resolve_misses"]
        assert first_misses > 0  # the cold boot actually resolved plans

        clear_resolve_caches()  # simulate a fresh process
        s2 = QBAServer(chunk_trials=8, cache_dir=cache_dir)
        assert s2.restored_plans == first_misses
        r2 = serve_batch(s2, stream)
        # The acceptance criterion: zero compile probes AND zero
        # resolver misses on the second boot.
        assert PROBE_STATS["compile_probes"] == 0
        assert PROBE_STATS["resolve_misses"] == 0
        assert PROBE_STATS["resolve_hits"] > 0
        assert [r.success for r in r1] == [r.success for r in r2]
        for res in r2:
            assert res.manifest["restored_plans"] == first_misses
    finally:
        clear_resolve_caches()


def test_saved_plans_feed_lint_configs(tmp_path):
    from qba_tpu.analysis.driver import saved_plan_configs

    cache_dir = str(tmp_path / "cache")
    clear_resolve_caches()
    try:
        server = QBAServer(chunk_trials=8, cache_dir=cache_dir)
        serve_batch(server, [
            _req("l0", n=4, L=8, d=1, trials=2),
            _req("l1", n=5, L=8, d=1, trials=2),
            _req("l2", n=4, L=8, d=1, trials=2, seed=99),  # same shape as l0
        ])
    finally:
        clear_resolve_caches()
    path = str(tmp_path / "cache" / "plans.json")
    cfgs = saved_configs(path)
    # One entry per *shape*, normalized over seed/trials.
    assert len(cfgs) == 2
    assert all(isinstance(c, QBAConfig) for c in cfgs)
    labeled = saved_plan_configs(path)
    assert {lbl for lbl, _ in labeled} == {
        "plan:4p-L8-d1", "plan:5p-L8-d1",
    }


def test_load_plans_tolerates_missing_or_garbage(tmp_path):
    from qba_tpu.serve.persist import load_plans

    assert load_plans(str(tmp_path / "nope")) == 0
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "plans.json").write_text("{not json")
    assert load_plans(str(bad)) == 0
    (bad / "plans.json").write_text(json.dumps({"schema": "other"}))
    assert load_plans(str(bad)) == 0


def test_save_plans_is_atomic_and_idempotent(tmp_path):
    cfg = QBAConfig(4, 8, 1, trials=3, seed=5)
    path = save_plans(str(tmp_path), [cfg, dataclasses.replace(cfg, seed=9)])
    assert saved_configs(path) == saved_configs(save_plans(str(tmp_path), [cfg]))
    assert not (tmp_path / "plans.json.tmp").exists()


def test_saved_mesh_round_trips_and_persists(tmp_path):
    # Round 9: the fleet mesh rides the plans.json artifact so the next
    # boot's admission prices against the sharded KI-2 ceiling the
    # warm-started plans assume.
    from qba_tpu.serve.persist import saved_mesh

    assert saved_mesh(str(tmp_path)) is None  # absent artifact
    cfg = QBAConfig(4, 8, 1, trials=3)
    save_plans(
        str(tmp_path), [cfg], mesh={"dp": 2, "tp": 4, "tp_comms": "ring"}
    )
    assert saved_mesh(str(tmp_path)) == {"dp": 2, "tp": 4, "tp_comms": "ring"}
    # A later save WITHOUT a mesh preserves the recorded one (a plain
    # resolver flush must not erase the fleet's placement metadata)...
    save_plans(str(tmp_path), [dataclasses.replace(cfg, seed=9)])
    assert saved_mesh(str(tmp_path)) == {"dp": 2, "tp": 4, "tp_comms": "ring"}
    # ...and an explicit new mesh replaces it.
    save_plans(
        str(tmp_path), mesh={"dp": 1, "tp": 8, "tp_comms": "all_gather"}
    )
    assert saved_mesh(str(tmp_path)) == {
        "dp": 1, "tp": 8, "tp_comms": "all_gather"
    }


# ---- LRU bound ---------------------------------------------------------


def test_resolve_cache_lru_eviction():
    old_cap = set_resolve_cache_cap(4)
    clear_resolve_caches()
    try:
        server = QBAServer(chunk_trials=8)
        serve_batch(server, [
            _req("e0", n=4, L=8, d=1, trials=2, engine="pallas_fused"),
            _req("e1", n=5, L=8, d=1, trials=2, engine="pallas_tiled"),
            _req("e2", n=4, L=16, d=1, trials=2, engine="pallas_fused"),
        ])
        info = resolve_cache_info()
        assert info["resolve_cache"]["cap"] == 4
        assert info["resolve_cache"]["size"] <= 4
        assert info["resolve_cache"]["evictions"] > 0
        assert (
            info["resolve_cache"]["evictions"]
            == PROBE_STATS["resolve_evictions"]
        )
    finally:
        set_resolve_cache_cap(old_cap)
        clear_resolve_caches()


def test_set_resolve_cache_cap_rejects_nonpositive():
    with pytest.raises(ValueError, match="cap"):
        set_resolve_cache_cap(0)


# ---- crash recovery: stale-claim reclaim + wall-clock deadline ---------


def _queue_dirs(tmp_path):
    import os

    qdir = tmp_path / "q"
    for d in ("inbox", "claimed", "done", "dead", "outbox"):
        os.makedirs(qdir / d)
    return qdir


def _inject_stale_claim(qdir, rid, age_s=3600.0, **kw):
    """A claim file left behind by a worker killed mid-request."""
    import os
    import time

    req = _req(rid, **kw)
    path = qdir / "claimed" / f"{rid}.json"
    path.write_text(json.dumps(req.to_json()))
    old = time.time() - age_s
    os.utime(path, (old, old))
    return req


def test_stale_claim_reclaimed_and_served(tmp_path):
    # A request claimed by a dead worker must be pushed back to the
    # inbox and served to completion — bit-identical to a direct run.
    from qba_tpu.serve.transport import serve_file_queue

    qdir = _queue_dirs(tmp_path)
    req = _inject_stale_claim(qdir, "stale0", trials=3, seed=6)
    server = QBAServer(chunk_trials=4)
    stats = serve_file_queue(
        server, str(qdir), poll_s=0.01, max_requests=1,
        reclaim_timeout_s=1.0,
    )
    assert stats["reclaimed"] == 1
    res = EvalResult.from_json(
        json.loads((qdir / "outbox" / "stale0.json").read_text())
    )
    assert res.error is None
    direct = run_trials(req.config(), trial_keys(req.config()))
    assert res.success == [bool(x) for x in np.asarray(direct.trials.success)]
    # Claim lifecycle: settled to done/, nothing left in claimed/.
    assert (qdir / "done" / "stale0.json").exists()
    assert not (qdir / "claimed" / "stale0.json").exists()


def test_fresh_claim_left_alone(tmp_path):
    # A claim younger than the timeout belongs to a live worker — the
    # reclaimer must not steal it.
    from qba_tpu.serve.transport import serve_file_queue

    qdir = _queue_dirs(tmp_path)
    _inject_stale_claim(qdir, "young0", age_s=0.0, trials=2)
    (qdir / "stop").touch()
    stats = serve_file_queue(
        QBAServer(chunk_trials=4), str(qdir), poll_s=0.01,
        reclaim_timeout_s=3600.0,
    )
    assert stats["reclaimed"] == 0
    assert (qdir / "claimed" / "young0.json").exists()
    assert not (qdir / "outbox" / "young0.json").exists()


def test_poison_claim_dead_lettered_with_structured_error(tmp_path):
    # After max_reclaims attempts the claim is quarantined in dead/ and
    # the outbox gets a structured error result — never an infinite
    # reclaim loop.
    from qba_tpu.serve.transport import serve_file_queue

    qdir = _queue_dirs(tmp_path)
    _inject_stale_claim(qdir, "poison0", trials=2)
    (qdir / "stop").touch()
    stats = serve_file_queue(
        QBAServer(chunk_trials=4), str(qdir), poll_s=0.01,
        reclaim_timeout_s=1.0, max_reclaims=0,
    )
    assert stats["reclaimed"] == 0
    res = EvalResult.from_json(
        json.loads((qdir / "outbox" / "poison0.json").read_text())
    )
    assert res.error and "dead-lettered" in res.error
    assert (qdir / "dead" / "poison0.json").exists()
    assert not (qdir / "claimed" / "poison0.json").exists()


def test_claim_restamps_mtime_so_peers_cannot_steal_backlog(tmp_path):
    # The claim rename preserves the producer's mtime (enqueue time),
    # which peer replicas would read as claim age: a request that
    # waited longer than the reclaim timeout in a shared inbox would
    # be "stale" the instant it was claimed and stolen from its live
    # claimant.  The claimant therefore re-stamps the claim file's
    # mtime to the claim instant — observable on the settled file in
    # done/, whose rename preserves it — while still attributing the
    # full enqueue-to-claim wait as queue_wait_s.
    import os
    import time

    from qba_tpu.serve.transport import serve_file_queue

    qdir = _queue_dirs(tmp_path)
    req = _req("old0", trials=2)
    path = qdir / "inbox" / "old0.json"
    path.write_text(json.dumps(req.to_json()))
    old = time.time() - 7200.0
    os.utime(path, (old, old))
    stats = serve_file_queue(
        QBAServer(chunk_trials=4), str(qdir), poll_s=0.01,
        max_requests=1, reclaim_timeout_s=5.0,
    )
    assert stats["reclaimed"] == 0
    res = json.loads((qdir / "outbox" / "old0.json").read_text())
    assert res["error"] is None
    assert res["queue_wait_s"] > 7000.0  # wait measured from enqueue
    # ...but the claim was re-stamped: its age never looked like 2h.
    assert time.time() - os.path.getmtime(qdir / "done" / "old0.json") < 600.0


def test_request_slug_is_injective_and_filesystem_safe():
    from qba_tpu.serve.queuefs import request_slug

    # Already-safe ids map to themselves (stable filenames everywhere).
    assert request_slug("plain-id_0.7") == "plain-id_0.7"
    assert request_slug("r7") == "r7"
    # Mangled ids must not collide with each other or with safe ids:
    # 'a/b' and 'a_b' sharing a filename would overwrite one request's
    # inbox file and resolve both futures from a single result.
    slugs = {request_slug(rid) for rid in ("a/b", "a:b", "a_b", "a.b")}
    assert len(slugs) == 4
    assert all("/" not in s and ":" not in s for s in slugs)
    # Deterministic, and the empty id doesn't alias a literal one.
    assert request_slug("a/b") == request_slug("a/b")
    assert request_slug("") != request_slug("request")


def test_request_slug_injectivity_fuzz():
    # Adversarial id pool: case pairs, sanitizer collisions, crafted
    # hash-suffix lookalikes, truncation-length tails, unicode and
    # lone-surrogate ids, plus a seeded random soup.  Distinct ids
    # must never share a queue filename, and every slug must be a
    # legal single filename component.
    import random

    from qba_tpu.serve.queuefs import _SLUG_MAX, request_slug

    long_base = "x" * (_SLUG_MAX + 50)
    ids = [
        # Case: safe ids map to themselves, so case must survive.
        "req-A", "req-a", "REQ-a", "Req-A",
        # Sanitizer collisions: all mangle toward 'a_b'.
        "a/b", "a:b", "a b", "a\tb", "a_b", "a\\b", "a\x00b",
        # Crafted lookalike of a hashed slug: a literal safe id equal
        # to sanitize('a/b') + separator + its digest must not alias
        # the real hashed slug of 'a/b'.
        request_slug("a/b").replace("~", "-"),
        "a_b-" + request_slug("a/b").rsplit("~", 1)[-1],
        # Truncation: ids differing only past the self-map length.
        long_base + "1", long_base + "2", long_base,
        long_base[: _SLUG_MAX], long_base[: _SLUG_MAX - 1],
        # Unicode: lookalikes, combining marks, surrogates, emoji.
        "héllo", "héllo", "hēllo", "Ω-req", "ω-req",
        "\ud800req", "req\udfff", "🐍", "🐍🐍", "",
        "request", "request~deadbeef00",
    ]
    rng = random.Random(1729)
    alphabet = "aA/_.:~ é́Ω🐍\x00-"
    ids += [
        "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 160)))
        for _ in range(300)
    ]
    slugs = {}
    for rid in ids:
        slug = request_slug(rid)
        # Filesystem-legal single component, bounded for NAME_MAX.
        assert slug and "/" not in slug and "\x00" not in slug
        assert slug not in (".", "..")
        assert len(slug.encode("utf-8", "surrogatepass")) <= 255
        assert request_slug(rid) == slug  # deterministic
        if slug in slugs and slugs[slug] != rid:
            raise AssertionError(
                f"slug collision: {rid!r} and {slugs[slug]!r} both "
                f"map to {slug!r}"
            )
        slugs[slug] = rid


def test_stop_sentinel_cannot_overtake_queued_requests(tmp_path):
    # Drain-before-stop FIFO: a stop sentinel that exists BEFORE the
    # worker's first poll must not make it exit with requests still
    # queued — the claim loop drains its inbox listing first, so every
    # already-enqueued request is served exactly once, in slug order.
    from qba_tpu.serve.transport import serve_file_queue

    qdir = _queue_dirs(tmp_path)
    for i in range(3):
        req = _req(f"s{i}", trials=2, seed=i)
        (qdir / "inbox" / f"s{i}.json").write_text(
            json.dumps(req.to_json())
        )
    (qdir / "stop").touch()  # stop is already there at boot
    stats = serve_file_queue(
        QBAServer(chunk_trials=4), str(qdir), poll_s=0.01,
    )
    assert stats["completed"] == 3
    for i in range(3):
        res = EvalResult.from_json(
            json.loads((qdir / "outbox" / f"s{i}.json").read_text())
        )
        assert res.error is None
        assert (qdir / "done" / f"s{i}.json").exists()
    assert list((qdir / "inbox").iterdir()) == []
    assert list((qdir / "claimed").iterdir()) == []


def test_reclaim_backoff_is_exponential(tmp_path):
    # k-th reclaim needs age >= timeout * 2**k: after one reclaim, a
    # claim of the same age is NOT immediately reclaimable again.
    from qba_tpu.serve.transport import _reclaim_stale, queue_paths

    qdir = _queue_dirs(tmp_path)
    _inject_stale_claim(qdir, "b0", age_s=1.5)
    paths = queue_paths(str(qdir))
    attempts: dict[str, int] = {}
    emitted: list = []
    n1 = _reclaim_stale(paths, attempts, set(), 1.0, 3, emitted.extend)
    assert n1 == 1 and attempts["b0.json"] == 1
    # Back in claimed/ at the same age: next threshold is 2.0s > 1.5s.
    (qdir / "inbox" / "b0.json").rename(qdir / "claimed" / "b0.json")
    import os
    import time

    old = time.time() - 1.5
    os.utime(qdir / "claimed" / "b0.json", (old, old))
    n2 = _reclaim_stale(paths, attempts, set(), 1.0, 3, emitted.extend)
    assert n2 == 0 and not emitted


def test_deadline_expiry_returns_structured_error_with_manifest():
    import time

    server = QBAServer(chunk_trials=4, deadline_s=0.01)
    server.submit(_req("dl0", trials=4))
    time.sleep(0.05)
    results = server.pump() + server.flush()
    [res] = [r for r in results if r.request_id == "dl0"]
    assert res.error and "deadline exceeded" in res.error
    validate_manifest(res.manifest)
    assert res.manifest["expired"] is True
    assert res.manifest["trials_completed"] == 0
    assert server.stats()["expired"] == 1
    # The scheduler holds no orphaned trials for the expired request.
    assert server.scheduler.pending_trials() == 0


def test_per_request_deadline_overrides_server_default():
    import time

    server = QBAServer(chunk_trials=4)  # no server-wide deadline
    server.submit(_req("fast", trials=2))
    server.submit(_req("slow", trials=2, deadline_s=0.01))
    time.sleep(0.05)
    by_id = {r.request_id: r for r in server.pump() + server.flush()}
    assert by_id["slow"].error and "deadline exceeded" in by_id["slow"].error
    assert by_id["fast"].error is None
    assert len(by_id["fast"].success) == 2


def test_server_rejects_nonpositive_deadline():
    with pytest.raises(ValueError, match="deadline_s"):
        QBAServer(chunk_trials=4, deadline_s=0.0)


def test_strategy_and_noise_split_buckets():
    # Strategy / noise knobs are part of the bucket identity (different
    # compiled programs must never share a bucket), and the strategy is
    # surfaced in the label.
    from qba_tpu.serve.scheduler import bucket_label

    base = QBAConfig(5, 8, 1, trials=7, seed=42)
    split = dataclasses.replace(base, strategy="split")
    noisy = dataclasses.replace(base, p_depolarize=0.05)
    assert bucket_config(base, 64) != bucket_config(split, 64)
    assert bucket_config(base, 64) != bucket_config(noisy, 64)
    assert bucket_label(bucket_config(split, 64)).endswith("-split")


def test_scheduler_cancel_removes_only_target_request():
    sched = BucketScheduler(8)
    cfg = QBAConfig(4, 8, 1, trials=4)
    rng = np.random.default_rng(0)
    for rid in ("keep", "drop"):
        sched.enqueue(
            rid, cfg,
            rng.integers(0, 2**32, size=(4, 2), dtype=np.uint32),
        )
    assert sched.cancel("drop") == 4
    assert sched.cancel("drop") == 0
    assert sched.pending_trials() == 4
    chunk = sched.next_chunk()
    assert {s.request_id for s in chunk.segments} == {"keep"}


# ---- latency summary ---------------------------------------------------


def test_span_latency_summary_percentiles():
    class S:
        def __init__(self, name, dur):
            self.name, self.dur = name, dur

    spans = [S("request", d) for d in (1.0, 2.0, 3.0, 4.0)] + [S("other", 99.0)]
    summary = span_latency_summary(spans, "request")
    assert summary["count"] == 4
    assert summary["p50_s"] == pytest.approx(2.5)
    assert summary["min_s"] == 1.0 and summary["max_s"] == 4.0
    assert summary["p99_s"] == pytest.approx(3.97)
    assert span_latency_summary([], "request") == {
        "name": "request", "count": 0,
    }


# ---- precision targets (docs/STATS.md) ---------------------------------


def test_targeted_request_finishes_early_with_prefix_identity():
    # One batch mixes a targeted and an untargeted request on the same
    # config: the targeted one must stop early with a certified anytime
    # CI, and its executed prefix must be bit-identical to the matching
    # slice of the untargeted (full-budget) run.
    server = QBAServer(chunk_trials=8)
    stream = [
        _req("tgt", trials=64, seed=3, target="decide vs 1/3 @ 95%"),
        _req("full", trials=64, seed=3),
    ]
    by_id = {r.request_id: r for r in serve_batch(server, stream)}
    tgt, full = by_id["tgt"], by_id["full"]
    assert tgt.error is None and full.error is None
    # Untargeted requests are untouched by the stats machinery.
    assert full.stop is None and full.ci is None
    assert full.n_trials == 64
    # The targeted request resolved before its budget.
    assert tgt.stop is not None
    assert tgt.stop["reason"] == "decided_above"
    assert tgt.stop["threshold"] == pytest.approx(1 / 3)
    assert tgt.n_trials == tgt.stop["n_trials"] < 64
    assert len(tgt.success) == tgt.n_trials
    # The estimate at the stopping time is anytime-valid and excludes
    # the threshold.
    assert tgt.ci["method"] == "mixture_martingale"
    assert tgt.ci["lo"] > 1 / 3
    # Prefix bit-identity: vs the served full-budget twin AND a direct
    # run of the same config (same seed -> same chunk keys).
    assert tgt.success == full.success[: tgt.n_trials]
    direct = run_trials(stream[0].config(), trial_keys(stream[0].config()))
    assert tgt.success == [
        bool(x) for x in np.asarray(direct.trials.success)[: tgt.n_trials]
    ]
    # Manifest: schema-valid, stats block pins target + stop + counts.
    validate_manifest(tgt.manifest)
    stats = tgt.manifest["stats"]
    assert stats["target"]["spec"] == "decide vs 1/3 @ 95%"
    assert stats["stop"]["reason"] == "decided_above"
    assert stats["trials_completed"] == tgt.n_trials
    assert stats["trials_requested"] == 64
    assert stats["success_rate"]["n"] == tgt.n_trials


def test_targeted_budget_exhausted_reports_partial_interval():
    # An unreachable width target inside the trial budget is an honest
    # non-answer: budget_exhausted, full prefix executed, and the (wide)
    # certified interval still attached.
    server = QBAServer(chunk_trials=4)
    [res] = serve_batch(
        server, [_req("tight", trials=8, seed=1, target="ci_width<=0.01")]
    )
    assert res.error is None
    assert res.stop["reason"] == "budget_exhausted"
    assert res.n_trials == 8 and len(res.success) == 8
    assert res.ci["method"] == "mixture_martingale"
    assert res.ci["hi"] - res.ci["lo"] > 0.01
    validate_manifest(res.manifest)
    assert res.manifest["stats"]["stop"]["reason"] == "budget_exhausted"


def test_invalid_target_becomes_error_result():
    # Target parse errors take the same intake path as a bad config:
    # a structured error result, and the stream keeps flowing.
    server = QBAServer(chunk_trials=4)
    results = serve_batch(
        server,
        [_req("bad", trials=4, target="decide maybe"), _req("ok", trials=4)],
    )
    by_id = {r.request_id: r for r in results}
    assert by_id["bad"].error and "unrecognized target" in by_id["bad"].error
    assert by_id["ok"].error is None and by_id["ok"].n_trials == 4


def test_targeted_deadline_expiry_reports_rule_silent():
    import time

    # The deadline fired, not the rule: the expired manifest carries the
    # target but stop is null, distinguishing "timed out" from "decided".
    server = QBAServer(chunk_trials=4, deadline_s=0.01)
    server.submit(_req("dl", trials=4, target="decide vs 1/3"))
    time.sleep(0.05)
    results = server.pump() + server.flush()
    [res] = [r for r in results if r.request_id == "dl"]
    assert res.error and "deadline exceeded" in res.error
    stats = res.manifest["stats"]
    assert stats["target"]["spec"] == "decide vs 1/3"
    assert stats["stop"] is None
    # No trials completed, so no partial interval either.
    assert res.stop is None and res.ci is None
