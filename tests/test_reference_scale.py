"""The reference's captured-run matrix at its ACTUAL scale.

All five runs the reference ships as fixtures use sizeL=1000
(`/root/reference/logs tests/log_3.txt` .. `log_d_11.txt` — list
indices reach 999, e.g. `log_11.txt:25`): 3 parties with {nobody, one
lieutenant, the commander} dishonest and 11 parties with {nobody, 5
including the commander} dishonest.  Rounds 1-3 exercised these
property classes only at reduced sizes (tests/test_e2e.py); this suite
runs them at full scale on the auto engine (VERDICT r3 item 3) and
asserts, per vmapped batch:

* **zero overflow** — the auto engine must serve these configs
  lossless, like the reference's unbounded Iprobe drain
  (`tfg.py:337-348`);
* **the oracle** — TrialResult.success re-derived independently from
  decisions + honesty must match the engine's verdict
  (`tfg.py:351-363`);
* **validity** — in the all-honest classes every lieutenant decides
  the commander's order.  With dishonest parties in play validity is
  NOT a guarantee (observed counterexample at 11p/5 with an honest
  commander; the round-5 study quantifies it — docs/VALIDITY.md: at
  sizeL=64 that configuration sits in the validity VALLEY, measured
  0.221 [0.210, 0.232], while the reference's own sizeL=1000 measures
  0.918), so the dishonest classes assert the oracle only — the
  hardest captured class being the dishonest-commander 11-party run
  (`log_d_11.txt:485-487`: Dishonests [7 5 1 11 2] include rank 1),
  which the batches must cover.

CPU note: these run the XLA engine (auto off-TPU); batch sizes are
sized to keep the suite's added wall time modest while covering every
class, including at least one dishonest-commander trial per dishonest
config (seeds chosen so the random dishonesty assignment hits it).
"""

import numpy as np
import pytest

from qba_tpu.backends.jax_backend import run_trials, trial_keys
from qba_tpu.config import QBAConfig

CASES = [
    # (n_parties, n_dishonest, trials, seed) — the five captured
    # configs' classes; the dishonest-commander classes emerge from
    # the random assignment within the dishonest batches.
    pytest.param(3, 0, 8, 0, id="3p_honest"),
    pytest.param(3, 1, 16, 1, id="3p_one_dishonest"),
    pytest.param(11, 0, 6, 0, id="11p_honest"),
    pytest.param(11, 5, 12, 2, id="11p_five_dishonest"),
]


@pytest.mark.parametrize("n_parties,n_dishonest,trials,seed", CASES)
def test_reference_scale_property_matrix(n_parties, n_dishonest, trials, seed):
    cfg = QBAConfig(
        n_parties=n_parties,
        size_l=1000,  # the reference's actual sizeL
        n_dishonest=n_dishonest,
        trials=trials,
        seed=seed,
    )
    res = run_trials(cfg, trial_keys(cfg))
    decisions = np.asarray(res.trials.decisions)  # [trials, n_parties]
    honest = np.asarray(res.trials.honest)  # [trials, n_parties]
    success = np.asarray(res.trials.success)
    overflow = np.asarray(res.trials.overflow)
    v_comm = np.asarray(res.trials.v_comm)

    # Lossless at reference scale on the auto engine.
    assert not overflow.any(), "auto engine overflowed at sizeL=1000"

    for t in range(trials):
        hon = honest[t]
        # The oracle, re-derived (tfg.py:351-363): success iff the
        # honest parties' decisions form a singleton.
        filtered = {int(d) for d, h in zip(decisions[t], hon) if h}
        assert bool(success[t]) == (len(filtered) == 1), (t, filtered)
        # Validity is a GUARANTEE only in the all-honest class (with
        # dishonest lieutenants in play, an honest commander's order
        # can still fail agreement — observed at 11p/5, and the
        # reference's captured matrix makes no claim there either).
        if n_dishonest == 0:
            assert int(decisions[t][0]) == int(v_comm[t])
            for i in range(1, n_parties):
                assert int(decisions[t][i]) == int(v_comm[t]), (
                    f"trial {t}: honest lieutenant {i} decided "
                    f"{int(decisions[t][i])} != v_comm {int(v_comm[t])}"
                )
            assert bool(success[t])

    if n_dishonest > 0:
        # The captured matrix includes dishonest-commander runs
        # (log_d_3.txt, log_d_11.txt): the batch must exercise that
        # class — the hardest one, where only the oracle remains.
        assert (~honest[:, 0]).any(), (
            "no dishonest-commander trial in this batch; bump the seed"
        )


def test_reference_scale_both_commander_classes_covered():
    """Aggregate coverage at the 11-party scale: a dishonest batch must
    exercise both commander classes (the reference captures the
    dishonest-commander one in log_d_11.txt — Dishonests [7 5 1 11 2]
    includes rank 1 — and its honest-commander runs elsewhere), and
    the engine's verdicts must satisfy the oracle in both."""
    cfg = QBAConfig(n_parties=11, size_l=1000, n_dishonest=5, trials=12, seed=5)
    res = run_trials(cfg, trial_keys(cfg))
    honest = np.asarray(res.trials.honest)
    success = np.asarray(res.trials.success)
    decisions = np.asarray(res.trials.decisions)
    hc = honest[:, 0]
    assert hc.any() and (~hc).any(), "batch must cover both classes"
    assert not np.asarray(res.trials.overflow).any()
    for t in range(cfg.trials):
        filtered = {
            int(d) for d, h in zip(decisions[t], honest[t]) if h
        }
        assert bool(success[t]) == (len(filtered) == 1)
