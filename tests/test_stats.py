"""Sequential-statistics subsystem tests (docs/STATS.md).

Five contracts:

* **Exactness** — Clopper–Pearson endpoints invert the closed-form
  binomial tails (computed here from ``math.comb``, independently of
  the stdlib incomplete-beta the implementation uses), and both
  interval families hit their nominal coverage at small n where it is
  computable exactly.
* **Error control** — the SPRT's realized wrong-decision rate under a
  fixed-seed simulation stays within its designed alpha/beta.
* **Determinism** — the adaptive allocator is a pure function of the
  observed counts (priority-then-index, no RNG): adaptive and uniform
  schedules yield bit-identical per-chunk results, only the order and
  the amount of work differ.
* **Prefix identity** — a precision-targeted ``run_sweep`` executes a
  bit-identical prefix of the fixed-budget run, resumes into the same
  state, and reports a typed, anytime-valid stop decision.
* **KI-8** — the manifest-CI lint flags bare rates and passes the
  manifests this repo actually produces.
"""

import dataclasses
import json
import math
import types

import numpy as np
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBACheckpointMismatch
from qba_tpu.stats import (
    AdaptiveAllocator,
    MixtureMartingaleCI,
    SPRT,
    StopDecision,
    clopper_pearson_ci,
    parse_target,
    rate_estimate,
    round_histogram,
    success_rate,
    wilson_ci,
)
from qba_tpu.stats.estimators import SweepEstimators
from qba_tpu.sweep import load_checkpoint, run_sweep, run_surface, save_checkpoint


def _binom_pmf(n, p, k):
    return math.comb(n, k) * p**k * (1.0 - p) ** (n - k)


def _tail_ge(n, p, k):
    return sum(_binom_pmf(n, p, j) for j in range(k, n + 1))


def _tail_le(n, p, k):
    return sum(_binom_pmf(n, p, j) for j in range(0, k + 1))


class TestEstimators:
    def test_success_rate_nan_on_zero_trials(self):
        assert math.isnan(success_rate(0, 0))
        assert success_rate(3, 4) == 0.75

    def test_vacuous_intervals_at_n_zero(self):
        assert wilson_ci(0, 0) == (0.0, 1.0)
        assert clopper_pearson_ci(0, 0) == (0.0, 1.0)
        est = rate_estimate(0, 0)
        assert est.to_json()["rate"] is None
        assert (est.lo, est.hi) == (0.0, 1.0)

    @pytest.mark.parametrize("k,n", [(1, 7), (7, 20), (3, 11), (19, 20)])
    def test_clopper_pearson_inverts_exact_binomial_tails(self, k, n):
        # The defining property, checked against math.comb sums (an
        # implementation-independent oracle for the beta identities):
        # at lo, P[X >= k] = alpha/2; at hi, P[X <= k] = alpha/2.
        lo, hi = clopper_pearson_ci(k, n, confidence=0.95)
        assert _tail_ge(n, lo, k) == pytest.approx(0.025, abs=1e-9)
        assert _tail_le(n, hi, k) == pytest.approx(0.025, abs=1e-9)

    def test_clopper_pearson_endpoint_cases(self):
        lo0, _ = clopper_pearson_ci(0, 9)
        _, hi9 = clopper_pearson_ci(9, 9)
        assert lo0 == 0.0 and hi9 == 1.0

    @pytest.mark.parametrize("p", [0.1, 0.37, 0.5, 0.9])
    def test_small_n_coverage_exact(self, p):
        # Exact coverage at n=12 by enumerating all 13 outcomes: CP is
        # >= nominal by construction; Wilson is allowed its documented
        # small-n dip but must stay close.
        n = 12
        cov_cp = sum(
            _binom_pmf(n, p, k)
            for k in range(n + 1)
            if clopper_pearson_ci(k, n)[0] <= p <= clopper_pearson_ci(k, n)[1]
        )
        cov_w = sum(
            _binom_pmf(n, p, k)
            for k in range(n + 1)
            if wilson_ci(k, n)[0] <= p <= wilson_ci(k, n)[1]
        )
        assert cov_cp >= 0.95
        assert cov_w >= 0.90

    def test_interval_validation(self):
        with pytest.raises(ValueError, match="0 <= k <= n"):
            wilson_ci(5, 4)
        with pytest.raises(ValueError, match="0 <= k <= n"):
            clopper_pearson_ci(-1, 4)
        with pytest.raises(ValueError, match="confidence"):
            wilson_ci(1, 4, confidence=1.5)
        with pytest.raises(ValueError, match="unknown CI method"):
            rate_estimate(1, 4, method="bayes")

    def test_sweep_estimators_overflow_is_per_chunk(self):
        chunks = [
            types.SimpleNamespace(trials=8, successes=6, overflow=False),
            types.SimpleNamespace(trials=8, successes=7, overflow=True),
        ]
        s = SweepEstimators().observe_all(chunks).summary()
        assert s["success_rate"]["k"] == 13
        assert s["success_rate"]["n"] == 16
        assert s["overflow_chunk_rate"]["k"] == 1
        assert s["overflow_chunk_rate"]["n"] == 2  # chunks, not trials
        # Manifest shape: every rate is a certified estimate.
        for key in ("success_rate", "overflow_chunk_rate"):
            assert {"lo", "hi", "method", "confidence"} <= set(s[key])

    def test_round_histogram_bins_and_total(self):
        bins = round_histogram([0, 0, 1, 3], n_rounds=3)
        assert [b["round"] for b in bins] == [0, 1, 2, 3]
        assert [b["k"] for b in bins] == [2, 1, 0, 1]
        assert all(b["n"] == 4 and "lo" in b and "hi" in b for b in bins)
        # Pre-counted mapping form agrees.
        from_map = round_histogram({0: 2, 1: 1, 3: 1}, n_rounds=3)
        assert from_map == bins


class TestSequentialRules:
    def test_stop_decision_rejects_unknown_reason(self):
        with pytest.raises(ValueError, match="unknown stop reason"):
            StopDecision(reason="vibes", n_trials=1, bound=0.0)

    def test_sprt_decides_fast_away_from_threshold(self):
        up = SPRT(threshold=1 / 3)
        up.observe(30, 32)
        dec = up.decision()
        assert dec is not None and dec.reason == "decided_above"
        assert dec.threshold == pytest.approx(1 / 3)
        assert dec.estimate.method == "mixture_martingale"
        down = SPRT(threshold=1 / 3)
        down.observe(0, 32)
        assert down.decision().reason == "decided_below"

    def test_sprt_chunk_aggregation_is_exact(self):
        # The LLR is linear in the success count: one observe(12, 40)
        # must equal four observe(3, 10).
        whole, parts = SPRT(threshold=0.5), SPRT(threshold=0.5)
        whole.observe(12, 40)
        for _ in range(4):
            parts.observe(3, 10)
        assert whole.llr == pytest.approx(parts.llr)

    def test_sprt_error_rate_under_simulation(self):
        # Fixed-seed simulation at the H1 boundary p = threshold+delta:
        # the fraction of runs that wrongly accept H0 is bounded by
        # beta's design value (0.05 here; the assertion allows the
        # simulation slack of 200 runs, and the seed makes it exact).
        rng = np.random.default_rng(20260805)
        threshold, delta = 0.5, 0.05
        wrong = undecided = 0
        for _ in range(200):
            sprt = SPRT(threshold=threshold, delta=delta)
            for _chunk in range(400):
                k = rng.binomial(16, threshold + delta)
                sprt.observe(int(k), 16)
                dec = sprt.decision()
                if dec is not None:
                    wrong += dec.reason == "decided_below"
                    break
            else:
                undecided += 1
        assert undecided == 0  # budget was ample
        assert wrong / 200 <= 0.06

    def test_martingale_ci_is_anytime_valid_on_fixed_seed(self):
        # One fixed-seed sample path at p=0.4: the running interval must
        # contain the truth at EVERY checkpoint (that is the sequence's
        # whole point), and the width must shrink.
        rng = np.random.default_rng(7)
        ci = MixtureMartingaleCI(confidence=0.95)
        widths = []
        for _ in range(50):
            ci.observe(int(rng.binomial(32, 0.4)), 32)
            lo, hi = ci.interval()
            assert lo <= 0.4 <= hi
            widths.append(hi - lo)
        assert widths[-1] < widths[0] / 3

    def test_martingale_width_rule_fires(self):
        ci = MixtureMartingaleCI(confidence=0.95, target_width=0.2)
        ci.observe(240, 480)
        dec = ci.decision()
        assert dec is not None and dec.reason == "ci_width"
        assert dec.bound <= 0.2
        assert dec.estimate.lo <= 0.5 <= dec.estimate.hi

    def test_exhausted_reports_partial_interval(self):
        ci = MixtureMartingaleCI(confidence=0.95, target_width=0.001)
        ci.observe(3, 8)
        assert ci.decision() is None
        dec = ci.exhausted()
        assert dec.reason == "budget_exhausted"
        assert dec.n_trials == 8
        assert dec.estimate.width == pytest.approx(dec.bound)


class TestTargetGrammar:
    def test_decide_with_fraction_and_defaults(self):
        t = parse_target("decide vs 1/3")
        assert t.kind == "decide"
        assert t.threshold == pytest.approx(1 / 3)
        assert t.confidence == 0.95 and t.delta == 0.05
        assert isinstance(t.make_rule(), SPRT)

    def test_decide_with_delta_and_confidence(self):
        t = parse_target("decide vs 0.5 +-0.1 @ 99%")
        assert (t.threshold, t.delta, t.confidence) == (0.5, 0.1, 0.99)

    def test_ci_width_target(self):
        t = parse_target("ci_width<=0.02 @ 90%")
        assert t.kind == "ci_width"
        assert (t.width, t.confidence) == (0.02, 0.90)
        rule = t.make_rule()
        assert isinstance(rule, MixtureMartingaleCI)
        assert rule.target_width == 0.02

    @pytest.mark.parametrize("bad", [
        "decide vs 2", "decide vs 1/0", "ci_width<=0", "decide 1/3",
        "ci_width<=0.1 @ 200%", "run until done",
    ])
    def test_rejects_malformed_specs(self, bad):
        with pytest.raises(ValueError):
            parse_target(bad)

    def test_round_trips_spec_in_json(self):
        t = parse_target("decide vs 1/3 @ 95%")
        assert t.to_json()["spec"] == "decide vs 1/3 @ 95%"


class TestAdaptiveAllocator:
    def test_bootstrap_then_uncertainty_order(self):
        target = parse_target("ci_width<=0.05")
        alloc = AdaptiveAllocator(["a", "b", "c"], target, budget_chunks=10)
        # Every cell gets one chunk before any cell gets two; b comes
        # back maximally uncertain, a and c nearly resolved.
        first = []
        for k, n in [(0, 400), (8, 16), (400, 400)]:
            cell = alloc.next_cell()
            first.append(cell)
            alloc.record(cell, k, n)
        assert first == [0, 1, 2]
        assert alloc.next_cell() == 1
        assert [t["reason"] for t in alloc.trace[:3]] == ["bootstrap"] * 3

    def test_decide_target_prioritizes_straddling_cells(self):
        target = parse_target("decide vs 1/3")
        alloc = AdaptiveAllocator(["low", "near"], target, budget_chunks=10)
        alloc.next_cell(), alloc.record(0, 1, 64)   # far below 1/3
        alloc.next_cell(), alloc.record(1, 6, 16)   # CI straddles 1/3
        nxt = alloc.next_cell()
        assert nxt == 1
        assert alloc.trace[-1]["reason"] == "straddling"

    def test_budget_exhaustion_and_finish(self):
        target = parse_target("ci_width<=0.0001")
        alloc = AdaptiveAllocator(["a"], target, budget_chunks=2)
        for _ in range(2):
            idx = alloc.next_cell()
            alloc.record(idx, 4, 8)
        assert alloc.next_cell() is None
        alloc.finish()
        (dec,) = alloc.decisions()
        assert dec.reason == "budget_exhausted"
        s = alloc.summary()
        assert s["spent_chunks"] == 2 and s["budget_chunks"] == 2
        assert s["cells"][0]["decision"]["reason"] == "budget_exhausted"

    def test_deterministic_replay(self):
        # Same counts in => same schedule and trace out; no RNG anywhere.
        target = parse_target("decide vs 1/3")
        # Bootstrap gives 0 then 1; afterwards cell 0 (counts near 1/3)
        # stays in the straddling tier and keeps winning.
        counts = [(0, 3, 8), (1, 7, 8), (0, 2, 8), (0, 2, 8)]

        def drive():
            alloc = AdaptiveAllocator(["x", "y"], target, budget_chunks=4)
            for want_cell, k, n in counts:
                got = alloc.next_cell()
                assert got == want_cell
                alloc.record(got, k, n)
            return alloc.trace

        assert drive() == drive()

    def test_preload_traces_resume(self):
        target = parse_target("ci_width<=0.5")
        alloc = AdaptiveAllocator(["a", "b"], target, budget_chunks=4)
        alloc.preload(0, 4, 8)
        assert alloc.trace[0]["reason"] == "resume"
        assert alloc.spent_chunks == 1

    def test_validation(self):
        target = parse_target("decide vs 1/3")
        with pytest.raises(ValueError, match="at least one cell"):
            AdaptiveAllocator([], target, budget_chunks=1)
        with pytest.raises(ValueError, match="budget_chunks"):
            AdaptiveAllocator(["a"], target, budget_chunks=0)


def _coin_runner(p=0.75):
    """Cheap deterministic fake runner: success bits drawn from the
    chunk's own key tree (same keys => same bits, like the real
    engines), overflow never."""
    import jax

    def runner(cfg, keys):
        bits = jax.random.bernoulli(keys[0], p, (keys.shape[0],))
        return types.SimpleNamespace(
            success=np.asarray(bits),
            overflow=np.zeros(keys.shape[0], dtype=bool),
        )

    return runner


class TestTargetedSweep:
    def test_targeted_run_is_bit_identical_prefix_of_fixed(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=16, seed=5)
        fixed = run_sweep(cfg, n_chunks=8, chunk_trials=16,
                          runner=_coin_runner())
        tgt = run_sweep(cfg, n_chunks=8, chunk_trials=16,
                        runner=_coin_runner(),
                        target="decide vs 1/3 @ 95%")
        assert tgt.stop is not None and tgt.stop.decided
        assert len(tgt.chunks) < len(fixed.chunks)  # strictly fewer trials
        assert tgt.chunks == fixed.chunks[: len(tgt.chunks)]
        # The anytime CI at stop excludes the threshold.
        est = tgt.stop.estimate
        assert est.lo > 1 / 3

    def test_budget_exhausted_is_an_honest_answer(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=8, seed=5)
        res = run_sweep(cfg, n_chunks=2, chunk_trials=8,
                        runner=_coin_runner(),
                        target="ci_width<=0.0001")
        assert res.stop.reason == "budget_exhausted"
        assert res.n_trials == 16
        summary = res.stats_summary()
        assert summary["stop"]["reason"] == "budget_exhausted"
        assert summary["success_rate"]["n"] == 16

    def test_targeted_resume_lands_in_identical_state(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=16, seed=5)
        ckpt = str(tmp_path / "t.json")
        solo = run_sweep(cfg, n_chunks=12, chunk_trials=4,
                         runner=_coin_runner(),
                         target="decide vs 1/3 @ 95%")
        assert solo.stop.decided and len(solo.chunks) > 1
        # Interrupted run: budget of 1 chunk, then resume with the full
        # budget — same chunks, same stop as the uninterrupted run.
        part = run_sweep(cfg, n_chunks=1, chunk_trials=4,
                         runner=_coin_runner(), checkpoint=ckpt,
                         target="decide vs 1/3 @ 95%")
        assert part.stop.reason == "budget_exhausted"
        res = run_sweep(cfg, n_chunks=12, chunk_trials=4,
                        runner=_coin_runner(), checkpoint=ckpt,
                        target="decide vs 1/3 @ 95%")
        assert res.resumed_chunks == 1
        assert res.chunks == solo.chunks
        assert res.stop.reason == solo.stop.reason
        assert res.stop.n_trials == solo.stop.n_trials
        # The checkpoint carries the target + stop stats block.
        payload = json.loads((tmp_path / "t.json").read_text())
        assert payload["stats"]["target"]["spec"] == "decide vs 1/3 @ 95%"

    def test_checkpoint_mismatch_is_typed_and_forceable(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=4, seed=2)
        ckpt = str(tmp_path / "c.json")
        run_sweep(cfg, n_chunks=1, chunk_trials=4, runner=_coin_runner(),
                  checkpoint=ckpt)
        with pytest.raises(QBACheckpointMismatch) as ei:
            load_checkpoint(ckpt, cfg, 8)
        err = ei.value
        assert isinstance(err, ValueError)  # existing pins keep working
        assert err.kind == "chunk_trials" and err.forceable
        assert (err.checkpoint_fingerprint, err.requested_fingerprint) == (4, 8)
        # --resume-force: warn, discard, re-chunk.
        with pytest.warns(QBACheckpointMismatch, match="resume-force"):
            assert load_checkpoint(ckpt, cfg, 8, force=True) == []

    def test_config_mismatch_never_forceable(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=4)
        ckpt = str(tmp_path / "c.json")
        run_sweep(cfg, n_chunks=1, chunk_trials=4, runner=_coin_runner(),
                  checkpoint=ckpt)
        other = dataclasses.replace(cfg, n_dishonest=1)
        with pytest.raises(QBACheckpointMismatch) as ei:
            load_checkpoint(ckpt, other, 4, force=True)
        assert ei.value.kind == "config" and not ei.value.forceable


class TestTargetedSurface:
    def test_adaptive_vs_uniform_differential(self, tmp_path):
        # Two cells with very different uncertainty: adaptive allocation
        # runs DIFFERENT chunk counts per cell, but every chunk it does
        # run is bit-identical to the uniform sweep's chunk of the same
        # index (keys are a pure function of (seed, chunk), so the
        # schedule can never change the data).
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1, trials=16,
                        seed=11)

        def runner(cfg, keys):
            # Easy cell at size_l=4 (rate ~0.97), hard cell at size_l=8
            # (rate ~0.5, wide CI forever).
            return _coin_runner(0.97 if cfg.size_l == 4 else 0.5)(cfg, keys)

        kw = dict(
            strategies=["reference"], noise_points=[(0.0, 0.0)],
            size_ls=[4, 8], chunk_trials=16, runner=runner,
            with_manifest=False,
        )
        uniform = run_surface(cfg, n_chunks=8, **kw)
        adaptive = run_surface(
            cfg, n_chunks=8, target="ci_width<=0.3 @ 95%",
            budget_chunks=8, **kw,
        )
        by_l = {c.size_l: c for c in adaptive}
        uni_by_l = {c.size_l: c for c in uniform}
        # The allocator spent more of the shared budget on the hard cell.
        assert len(by_l[8].result.chunks) > len(by_l[4].result.chunks)
        # Bit-identical chunk results wherever both schedules ran.
        for L in (4, 8):
            got = by_l[L].result.chunks
            assert got == uni_by_l[L].result.chunks[: len(got)]
        assert by_l[4].result.stop.reason == "ci_width"

    def test_surface_manifest_carries_stats_and_allocator(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1, trials=16,
                        seed=3)
        cells = run_surface(
            cfg, strategies=["reference"], noise_points=[(0.0, 0.0)],
            size_ls=[4], n_chunks=2, chunk_trials=16,
            runner=_coin_runner(), target="decide vs 1/3 @ 95%",
        )
        (cell,) = cells
        stats = cell.manifest["stats"]
        assert stats["target"]["spec"] == "decide vs 1/3 @ 95%"
        assert stats["stop"]["reason"] in (
            "decided_above", "decided_below", "budget_exhausted",
        )
        assert {"lo", "hi"} <= set(stats["success_rate"])
        alloc = stats["allocator"]
        assert alloc["spent_chunks"] <= alloc["budget_chunks"]
        assert alloc["trace"][0]["reason"] == "bootstrap"
        from qba_tpu.obs.manifest import validate_manifest

        validate_manifest(cell.manifest)


class TestManifestLint:
    def test_bare_rate_is_flagged_certified_is_not(self):
        from qba_tpu.analysis.manifests import check_manifest

        bad = {
            "success_rate": 0.9,
            "nested": [{"drop_ratio": 1}],
            "ok_rate": {"rate": 0.5, "lo": 0.4, "hi": 0.6},
            "p_depolarize": 0.05,     # config input, not a measurement
            "enable_rate": True,      # bool is not a numeric rate
        }
        report = check_manifest(bad, label="fixture")
        flagged = {f.where for f in report.findings}
        assert flagged == {"success_rate", "nested[0].drop_ratio"}
        assert all(f.ki == "KI-8" for f in report.findings)

    def test_certified_estimate_fields_not_descended(self):
        from qba_tpu.analysis.manifests import check_manifest

        ok = {"stats": {"success_rate": {
            "rate": None, "lo": 0.0, "hi": 1.0, "method": "wilson",
        }}}
        assert check_manifest(ok).ok

    def test_missing_file_is_a_finding(self, tmp_path):
        from qba_tpu.analysis.manifests import check_manifest_files

        report = check_manifest_files([str(tmp_path / "nope.json")])
        assert not report.ok
        assert "does not exist" in report.findings[0].message

    def test_produced_sweep_manifest_is_clean(self, tmp_path):
        # The repo's own telemetry output must pass its own gate.
        import io

        from qba_tpu.analysis.manifests import check_manifest_files
        from qba_tpu.cli import main

        tel = str(tmp_path / "tel")
        rc = main(
            ["sweep", "--n-parties", "3", "--size-l", "4", "--trials", "8",
             "--n-chunks", "2", "--target", "decide vs 1/3",
             "--telemetry", tel],
            out=io.StringIO(),
        )
        assert rc == 0
        report = check_manifest_files([tel + "/run_manifest.json"])
        assert report.ok, report.render()
        assert report.stats["manifests_checked"] == 1
