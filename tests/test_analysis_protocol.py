"""KI-10 protocol model checker tests (docs/ANALYSIS.md).

Four contracts:

* **Shipped tree is verified** — the bounded BFS exhausts every
  default scenario with zero findings, the conformance sweep binds
  every queue mutation in ``serve/`` to a registered model
  transition, and the admission-purity proof holds.
* **Seeded races die with schedules** — the pre-PR-12 reclaim race
  and the double-emit reclaimer (``tests/analysis_fixtures/``) are
  each killed with a printed *minimal* counterexample naming the
  conflicting transitions.
* **The conformance gate is live** — an unregistered ``os.rename``
  on a queue path injected into a scratch copy of ``serve/`` turns
  the sweep red; stripping a registration annotation reports BOTH the
  unmapped mutation and the lost model site.
* **The BFS core is minimal** — the first witness per invariant is a
  shortest schedule (the property that makes counterexamples
  readable), proven on a toy system.
"""

import json
import os
import shutil

from qba_tpu.analysis import protocol
from qba_tpu.analysis.fsm import Action, Invariant, explore, render_schedule

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def _fixture(name: str) -> str:
    return os.path.join(FIXTURES, name)


# ---- fsm core ----------------------------------------------------------


def test_fsm_counterexample_is_minimal_and_rendered():
    # Counter with +1/+2 steps, capped at 6; >=5 is a violation.  BFS
    # must witness it at depth 3 (1+2+2 or 2+2+1), never depth 5.
    inc1 = Action("inc1", lambda s: [("+1", s + 1)] if s < 6 else [])
    inc2 = Action("inc2", lambda s: [("+2", s + 2)] if s < 6 else [])
    bad = Invariant(
        "lt5", lambda s, via: f"counter hit {s}" if s >= 5 else None
    )
    ex = explore(0, [inc1, inc2], [bad])
    assert not ex.truncated and not ex.ok
    v = ex.violations[0]
    assert v.depth == 3
    rendered = render_schedule(v.schedule)
    assert rendered.splitlines()[0].strip().startswith("1.")
    assert len(rendered.splitlines()) == 3


def test_fsm_terminal_invariants_run_on_quiescent_states_only():
    # One action drains a token; the terminal invariant requires the
    # token to be gone — it must not fire on the (non-quiescent)
    # initial state.
    drain = Action("drain", lambda s: [("drain", 0)] if s else [])
    done = Invariant(
        "drained",
        lambda s, via: "token left" if s else None,
        terminal=True,
    )
    assert explore(1, [drain], [done]).ok
    stuck = Action("noop", lambda s: [])
    assert not explore(1, [stuck], [done]).ok


# ---- shipped tree ------------------------------------------------------


def test_shipped_tree_protocol_clean_and_exhaustive():
    report = protocol.check_protocol()
    assert report.ok, report.render()
    assert report.stats["protocol_states_explored"] > 0
    assert report.stats["protocol_sites_bound"] == len(
        protocol.PROTOCOL_SITES
    )
    # Every scenario exhausted — a truncated clean run proves nothing.
    assert all("exhaustive" in n for n in report.notes if "protocol/" in n)


def test_shipped_semantics_extraction():
    sem = protocol.extract_semantics()
    assert sem.restamp_on_claim  # the PR-12 fix is present
    assert sem.emit_only_at_dead_letter
    assert sem.stop_after_drain
    assert sem.origin == "serve/transport.py"


def test_every_marker_maps_to_a_model_action():
    for _file, _fn, marker in protocol.PROTOCOL_SITES:
        assert marker in protocol.MARKER_TO_ACTION


# ---- seeded violation fixtures ----------------------------------------


def test_bad_reclaim_race_fixture_killed_with_schedule():
    path = _fixture("bad_reclaim_race.py")
    sem = protocol.extract_semantics(overlay=path)
    assert not sem.restamp_on_claim  # the seeded bug was extracted
    report = protocol.check_protocol_fixture(path)
    assert not report.ok
    msgs = [f.message for f in report.findings]
    # The race manifests as a double execution; the minimal schedule
    # names both the steal and the re-claim.
    race = [m for m in msgs if "concurrently" in m]
    assert race, msgs
    m = race[0]
    assert "minimal counterexample" in m
    assert "reclaim(" in m and "claim(" in m
    assert "NOT re-stamped" in m
    assert "conflicting transition" in m
    # Non-empty numbered schedule.
    assert any(line.strip().startswith("1.") for line in m.splitlines())
    # The fixture path halts at the first counterexample instead of
    # exhausting the (much larger) buggy state space.
    assert any("HALTED at first violation" in n for n in report.notes)


def test_bad_double_emit_fixture_killed_with_schedule():
    path = _fixture("bad_double_emit.py")
    sem = protocol.extract_semantics(overlay=path)
    assert not sem.emit_only_at_dead_letter
    report = protocol.check_protocol_fixture(path)
    assert not report.ok
    dup = [
        f.message
        for f in report.findings
        if "exactly-once" in f.message
    ]
    assert dup, [f.message for f in report.findings]
    m = dup[0]
    assert "minimal counterexample" in m
    assert "failure result" in m  # the spurious reclaim emit is named
    assert "conflicting transitions" in m


def test_fixture_schedules_are_minimal():
    # The reclaim race needs exactly 5 steps from boot (enqueue, age,
    # claim, steal, re-claim) — BFS must find that depth, not a longer
    # interleaving.
    report = protocol.check_protocol_fixture(_fixture("bad_reclaim_race.py"))
    race = [f for f in report.findings if "concurrently" in f.message]
    assert "(5 steps" in race[0].message


# ---- conformance gate --------------------------------------------------


def _scratch_serve(tmp_path):
    src = os.path.dirname(
        os.path.abspath(__import__("qba_tpu.serve", fromlist=["x"]).__file__)
    )
    dst = str(tmp_path / "serve")
    shutil.copytree(src, dst)
    return dst


def test_conformance_clean_on_scratch_copy(tmp_path):
    root = _scratch_serve(tmp_path)
    report = protocol.check_protocol_conformance(serve_root=root)
    assert report.ok, report.render()


def test_conformance_catches_unregistered_rename(tmp_path):
    # Inject an annotation-free os.rename on a queue path into a
    # protocol module: the gate must go red.
    root = _scratch_serve(tmp_path)
    with open(os.path.join(root, "fleet", "pool.py"), "a") as f:
        f.write(
            "\n\ndef _steal_claim(paths, name):\n"
            "    os.rename(\n"
            "        os.path.join(paths['inbox'], name),\n"
            "        os.path.join(paths['claimed'], name),\n"
            "    )\n"
        )
    report = protocol.check_protocol_conformance(serve_root=root)
    assert not report.ok
    hits = [f for f in report.findings if "unmapped queue mutation" in f.message]
    assert hits and "_steal_claim" in hits[0].message
    assert "pool.py" in hits[0].where


def test_conformance_catches_lost_registered_site(tmp_path):
    # Strip the restamp annotation: the same utime is now BOTH an
    # unmapped mutation and a lost registered model site.
    root = _scratch_serve(tmp_path)
    tpath = os.path.join(root, "transport.py")
    with open(tpath) as f:
        src = f.read()
    assert "# qba-protocol: restamp" in src
    with open(tpath, "w") as f:
        f.write(src.replace("# qba-protocol: restamp", "# (unregistered)"))
    report = protocol.check_protocol_conformance(serve_root=root)
    msgs = [f.message for f in report.findings]
    assert any("unmapped queue mutation os.utime" in m for m in msgs)
    assert any(
        "registered model site lost" in m and "'restamp'" in m
        for m in msgs
    )


def test_conformance_queue_token_heuristic(tmp_path):
    # Outside the five protocol modules only mutations whose arguments
    # mention queue paths are protocol mutations: persist.py's
    # plans.json temp-file shuffle stays exempt, but an inbox rename
    # added there is caught.
    root = _scratch_serve(tmp_path)
    ppath = os.path.join(root, "persist.py")
    assert protocol.check_protocol_conformance(serve_root=root).ok
    with open(ppath, "a") as f:
        f.write(
            "\n\ndef _sneaky(queue_dir, name):\n"
            "    os.rename(os.path.join(queue_dir, 'inbox', name), name)\n"
        )
    report = protocol.check_protocol_conformance(serve_root=root)
    assert any(
        "persist.py" in f.path and "unmapped" in f.message
        for f in report.findings
    )


def test_conformance_rejects_unknown_marker(tmp_path):
    root = _scratch_serve(tmp_path)
    with open(os.path.join(root, "fleet", "pool.py"), "a") as f:
        f.write(
            "\n\ndef _odd(paths, name):\n"
            "    # qba-protocol: teleport\n"
            "    os.rename(os.path.join(paths['inbox'], name), name)\n"
        )
    report = protocol.check_protocol_conformance(serve_root=root)
    assert any(
        "unknown protocol transition 'teleport'" in f.message
        for f in report.findings
    )


# ---- admission purity --------------------------------------------------


def test_admission_purity_flags_recording_poll(tmp_path):
    bad = tmp_path / "frontend_bad.py"
    bad.write_text(
        "async def _retry_deferred(self):\n"
        "    for req in self._deferred:\n"
        "        decision = self.admission.try_admit(req)\n"
        "        self.admission.record(decision)\n"
    )
    report = protocol.check_admission_purity(frontend_path=str(bad))
    assert not report.ok
    assert "record=False" in report.findings[0].message


def test_admission_purity_holds_on_shipped_frontend():
    assert protocol.check_admission_purity().ok


# ---- CLI + driver wiring ----------------------------------------------


def test_cli_lint_protocol_clean_with_json(tmp_path, capsys):
    from qba_tpu.cli import main

    out = tmp_path / "findings.json"
    rc = main([
        "lint", "--protocol", "--config", "5,4,1", "--engines", "xla",
        "-v", "--findings-json", str(out),
    ])
    assert rc == 0
    payload = json.loads(out.read_text())
    assert payload["ok"] and payload["protocol"]
    assert payload["stats"]["protocol_states_explored"] > 0
    stdout = capsys.readouterr().out
    assert "protocol/2w2r-crash" in stdout


def test_trace_cache_memoizes_per_config_engine():
    from qba_tpu.analysis import tracecache
    from qba_tpu.config import QBAConfig

    cfg = QBAConfig(5, 4, 1)
    tracecache.reset()
    try:
        closed_a, warns_a = tracecache.trial_jaxpr(cfg, "xla")
        assert tracecache.stats() == {
            "trace_cache_entries": 1,
            "trace_cache_hits": 0,
        }
        closed_b, warns_b = tracecache.trial_jaxpr(cfg, "xla")
        assert closed_b is closed_a
        assert warns_b == warns_a
        assert tracecache.stats()["trace_cache_hits"] == 1
        # A different engine is a different entry, never a stale hit.
        tracecache.trial_jaxpr(cfg, None)
        assert tracecache.stats()["trace_cache_entries"] == 2
    finally:
        tracecache.reset()
