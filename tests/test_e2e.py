"""End-to-end protocol tests: the captured-log configuration classes.

The reference's five captured runs (SURVEY §4) demonstrate behavior
*classes*; RNG differs (docs/DIVERGENCES.md D6), so we assert their
properties over Monte-Carlo batches rather than bitwise logs:

* ``log_3``   — 3 parties honest: unanimous decision == commander's v.
* ``log_d_3`` / ``log_dC_3`` — 3 parties, 1 dishonest (incl. the dishonest-
  commander case): honest parties still agree.
* ``log_11``  — 11 parties honest: unanimous.
* ``log_d_11`` — 11 parties, 5 dishonest incl. the commander:
  TestManyDishonest below.  At this adversary density success is
  *probabilistic in the security parameter*: forged corrupt-v packets
  pass ``consistent`` with probability ≈ (1-p)^(|L|·|P|), so the success
  rate is U-shaped in ``size_l`` (tiny |P| → forgeries die on the
  tuple-length check; |P| ≈ 2-8 → forgery window; reference scale
  sizeL=1000, |P| ≈ 31 → forgeries rejected, measured rate ≈ 0.9 —
  consistent with the reference's single successful captured run).
  What IS deterministic is validity: an honest commander's order is
  accepted by every honest lieutenant in step 3a (own sub-list elements
  ``v ^ rands[0] ^ rands[i-1]`` never equal ``v``), regardless of the
  adversary.
"""

import jax
import jax.numpy as jnp

from qba_tpu.config import QBAConfig
from qba_tpu.rounds import run_trial


def batch(cfg, seed, n):
    keys = jax.random.split(jax.random.key(seed), n)
    return jax.jit(jax.vmap(lambda k: run_trial(cfg, k)))(keys)


class TestHonestRuns:
    def test_log3_class_unanimous_on_v(self):
        cfg = QBAConfig(n_parties=3, size_l=16, n_dishonest=0)
        r = batch(cfg, 0, 64)
        assert float(jnp.mean(r.success)) == 1.0
        # validity, not just agreement: every decision equals the
        # commander's order (log_3.txt:23-25)
        assert bool(jnp.all(r.decisions == r.v_comm[:, None]))
        assert not bool(jnp.any(r.overflow))

    def test_log11_class_unanimous(self):
        cfg = QBAConfig(n_parties=11, size_l=16, n_dishonest=0)
        r = batch(cfg, 1, 16)
        assert float(jnp.mean(r.success)) == 1.0
        assert bool(jnp.all(r.decisions == r.v_comm[:, None]))


class TestOneDishonest:
    def test_log_d3_and_dC3_classes_agree(self):
        cfg = QBAConfig(n_parties=3, size_l=64, n_dishonest=1)
        r = batch(cfg, 2, 128)
        assert float(jnp.mean(r.success)) == 1.0
        # the batch must include dishonest-commander trials (~1/3)
        comm_dishonest = ~r.honest[:, 0]
        assert int(jnp.sum(comm_dishonest)) > 20

    def test_dishonest_commander_can_split_orders(self):
        # Among commander-dishonest trials, honest lieutenants sometimes
        # accept BOTH equivocated orders and decide their min
        # (log_dC_3.txt:25-27: V = {0, 3} -> 0).
        cfg = QBAConfig(n_parties=3, size_l=64, n_dishonest=1)
        r = batch(cfg, 3, 256)
        comm_dishonest = ~r.honest[:, 0]
        both = jnp.sum(r.vi, axis=-1) >= 2  # [trials, n_lieu]
        saw_split = bool(jnp.any(comm_dishonest & jnp.any(both, axis=-1)))
        assert saw_split


class TestManyDishonest:
    def test_log_d11_class_validity_and_oracle(self):
        # log_d_11 class at reduced size: 11 parties, 5 dishonest
        # (commander included with prob 5/11 per trial).
        cfg = QBAConfig(n_parties=11, size_l=64, n_dishonest=5)
        r = batch(cfg, 5, 16)
        # Validity (deterministic, see module docstring): honest commander's
        # v is in every honest lieutenant's accepted-set.
        comm_honest = r.honest[:, 0]  # [trials]
        v_accepted = jnp.take_along_axis(
            r.vi, r.v_comm[:, None, None], axis=-1
        )[..., 0]  # [trials, n_lieu]
        lieu_honest = r.honest[:, 1:]
        assert bool(jnp.all(~comm_honest[:, None] | ~lieu_honest | v_accepted))
        # The success flag must agree with the decisions it summarizes.
        for t in range(16):
            hd = {int(d) for d, h in zip(r.decisions[t], r.honest[t]) if bool(h)}
            assert bool(r.success[t]) == (len(hd) == 1)


class TestDeterminism:
    def test_same_key_same_result(self):
        cfg = QBAConfig(n_parties=3, size_l=16, n_dishonest=1)
        a = run_trial(cfg, jax.random.key(9))
        b = run_trial(cfg, jax.random.key(9))
        assert a.decisions.tolist() == b.decisions.tolist()
        assert bool(a.success) == bool(b.success)


class TestSlotBound:
    def test_reduced_slots_runs_and_flags(self):
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_accepts_per_round=1
        )
        r = batch(cfg, 4, 32)
        # protocol still completes; overflow flag is a recorded diagnostic
        assert r.success.shape == (32,)
        assert r.overflow.dtype == jnp.bool_


class TestLargeScale:
    def test_33_parties_all_honest_unanimous(self):
        # The 48-qubit-class scale (nQubits=6, w=64) where the reference's
        # dense joint circuit is infeasible; the factorized sampler
        # (SURVEY §2.6) makes it routine.  All honest -> validity: every
        # party decides the commander's order.
        cfg = QBAConfig(n_parties=33, size_l=16, n_dishonest=0)
        r = batch(cfg, 11, 4)
        assert float(jnp.mean(r.success.astype(jnp.float32))) == 1.0
        assert bool(jnp.all(r.decisions == r.v_comm[:, None]))
