"""Fleet-subsystem tests (docs/SERVING.md "Fleet").

Five contracts:

* **Pricing determinism** — :meth:`Target.planning_trials` is pure
  arithmetic: same target, same price; tighter precision prices more
  trials; the request budget is a hard cap.
* **Admission determinism** — the admit/defer/reject decision sequence
  is a pure function of the request sequence and the settle points: a
  fixed stream replayed through a fresh controller yields the
  bit-identical decision list, with typed reasons.
* **Attribution** — a result served through the file queue carries the
  serving replica's id and its queue wait, in the wire result AND the
  validated manifest, and each replica writes its own exit summary.
* **Fleet bit-identity** — a request answered through the full socket
  front-end + admission + file-queue worker stack equals a direct
  single-process :func:`serve_batch` run trial for trial.
* **Artifact merge** — concurrent-style saves to one ``plans.json``
  union their resolver states and config shapes instead of clobbering
  (the property that makes a shared warm-start artifact safe for N
  replicas).
"""

import json
import os
import socket
import threading
import time

import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.serve import EvalRequest, QBAServer, serve_batch
from qba_tpu.serve.fleet import (
    ADMIT,
    DEFER,
    REASONS,
    REJECT,
    AdmissionController,
    FleetFrontend,
    ReplicaPool,
    fleet_summary,
    make_device_env,
)
from qba_tpu.serve.transport import serve_file_queue
from qba_tpu.stats import parse_target


def _req(rid, n=4, L=4, d=0, trials=4, seed=0, **kw):
    return EvalRequest(
        request_id=rid, n_parties=n, size_l=L, n_dishonest=d,
        trials=trials, seed=seed, **kw,
    )


# ---- pricing -----------------------------------------------------------


def test_planning_trials_deterministic_and_budget_capped():
    t = parse_target("decide vs 1/3")
    assert t.planning_trials(10_000) == t.planning_trials(10_000)
    # The Wald bound at the 1/3 boundary with default delta/confidence
    # is a few hundred trials — well under a 10k budget, over a 10-trial
    # one (the budget is a hard cap, and the floor is one trial).
    price = t.planning_trials(10_000)
    assert 10 < price < 10_000
    assert t.planning_trials(10) == 10
    assert t.planning_trials(1) == 1
    with pytest.raises(ValueError):
        t.planning_trials(0)


def test_planning_trials_monotone_in_precision():
    loose = parse_target("decide vs 1/3 +-0.1").planning_trials(10**6)
    tight = parse_target("decide vs 1/3 +-0.02").planning_trials(10**6)
    assert tight > loose
    wide = parse_target("ci_width<=0.1").planning_trials(10**7)
    narrow = parse_target("ci_width<=0.01").planning_trials(10**7)
    assert narrow > wide
    # Higher confidence prices more trials too.
    p95 = parse_target("ci_width<=0.05 @ 95%").planning_trials(10**7)
    p99 = parse_target("ci_width<=0.05 @ 99%").planning_trials(10**7)
    assert p99 > p95


# ---- admission ---------------------------------------------------------


def _decision_stream(ac):
    """A fixed request sequence with a mid-stream settle; returns the
    decision JSON list (capacity 16: chunk_trials=8 * window_chunks=2)."""
    out = [
        ac.try_admit(_req("A", trials=16)),   # admit, fills the window
        ac.try_admit(_req("B", trials=8)),    # defer: window full
        ac.try_admit(_req("C", trials=24)),   # reject: > whole window
        ac.try_admit(_req("bad", n=0)),       # reject: invalid config
    ]
    ac.settle("A", executed_trials=16)
    out.append(ac.try_admit(_req("B", trials=8)))  # now admits
    return [d.to_json() for d in out]


def _controller(**kw):
    kw.setdefault("chunk_trials", 8)
    kw.setdefault("replicas", 1)
    kw.setdefault("window_chunks", 2)
    return AdmissionController(**kw)


def test_admission_decision_sequence_is_deterministic():
    first = _decision_stream(_controller())
    second = _decision_stream(_controller())
    assert first == second  # pure function of stream + settle points
    actions = [(d["action"], d["reason"]) for d in first]
    assert actions == [
        (ADMIT, "capacity_available"),
        (DEFER, "window_full"),
        (REJECT, "oversized_request"),
        (REJECT, "invalid_request"),
        (ADMIT, "capacity_available"),
    ]
    assert all(d["reason"] in REASONS for d in first)
    # The ledger is visible in every decision: A's admit filled the
    # 16-trial window; B's post-settle admit sees it drained.
    assert first[0]["outstanding_trials"] == 16
    assert first[-1]["outstanding_trials"] == 8


def test_admission_prices_targets_below_budget():
    ac = _controller(window_chunks=64)
    dec = ac.try_admit(_req("T", trials=4096, target="decide vs 1/3"))
    assert dec.action == ADMIT
    # Chunk-quantized Wald price, not the full 4096-trial budget.
    assert dec.priced_trials % 8 == 0
    assert dec.priced_trials < 4096
    untargeted = ac.try_admit(_req("U", trials=24))
    assert untargeted.priced_trials == 24  # already chunk-aligned


def test_admission_rejects_unservable_shape():
    # With (essentially) no HBM the KI-2 ceiling is below one chunk:
    # the shape can never execute, so it must be rejected up front —
    # not parked in the queue to wedge a replica.
    ac = _controller(hbm_bytes=1)
    dec = ac.try_admit(_req("huge", trials=8))
    assert (dec.action, dec.reason) == (REJECT, "unservable_shape")
    assert ac.outstanding_trials == 0


def test_retry_defers_do_not_inflate_decision_ledger():
    # The front-end's deferred-retry loop re-polls the deferred head on
    # every settle event; those polls run with record=False so the
    # decision list stays a pure function of the request stream and
    # settle points — not of how many settles fired while a request
    # waited.  Only the retry that resolves is recorded, via record().
    ac = _controller()  # capacity 16
    ac.try_admit(_req("A", trials=16))
    assert ac.try_admit(_req("B", trials=8)).action == DEFER
    for _ in range(5):
        dec = ac.try_admit(_req("B", trials=8), record=False)
        assert dec.action == DEFER
    assert len(ac.decisions) == 2  # the admit + the one intake DEFER
    ac.settle("A")
    dec = ac.try_admit(_req("B", trials=8), record=False)
    assert dec.action == ADMIT
    ac.record(dec)
    assert [d.action for d in ac.decisions] == [ADMIT, DEFER, ADMIT]
    assert ac.outstanding_trials == 8  # record=False still prices admits


def test_admission_settle_is_idempotent_and_releases():
    ac = _controller()
    ac.try_admit(_req("A", trials=16))
    assert ac.settle("A") == 16
    assert ac.settle("A") == 0  # double-settle releases nothing
    assert ac.settle("never-admitted") == 0
    s = ac.summary()
    assert s["released_trials"] == 16
    assert s["outstanding_trials"] == 0
    assert s["by_action"] == {ADMIT: 1}


# ---- attribution through the file queue --------------------------------


def _queue_dirs(tmp_path):
    qdir = tmp_path / "q"
    for d in ("inbox", "claimed", "done", "dead", "outbox"):
        os.makedirs(qdir / d)
    return qdir


def test_result_and_manifest_carry_replica_and_queue_wait(tmp_path):
    qdir = _queue_dirs(tmp_path)
    req = _req("w0", trials=3, seed=5)
    (qdir / "inbox" / "w0.json").write_text(json.dumps(req.to_json()))
    server = QBAServer(chunk_trials=4, replica_id="r7")
    stats = serve_file_queue(server, str(qdir), poll_s=0.01, max_requests=1)
    res = json.loads((qdir / "outbox" / "w0.json").read_text())
    assert res["error"] is None
    assert res["replica_id"] == "r7"
    assert res["queue_wait_s"] >= 0.0
    # Attribution is in the validated manifest too, not just the wire.
    assert res["manifest"]["replica_id"] == "r7"
    assert res["manifest"]["queue_wait_s"] == res["queue_wait_s"]
    # Per-replica exit summary: summary-<id>.json, never summary.json
    # (N replicas share the queue dir and must not clobber each other).
    assert stats["replica_id"] == "r7"
    assert (qdir / "summary-r7.json").exists()
    assert not (qdir / "summary.json").exists()
    summary = json.loads((qdir / "summary-r7.json").read_text())
    assert summary["replica_id"] == "r7"
    assert summary["queue_wait"]["count"] == 1


def test_queue_wait_summary_in_server_stats():
    server = QBAServer(chunk_trials=4, replica_id="rq")
    server.submit(_req("q0", trials=2), queue_wait_s=0.25)
    server.flush()
    stats = server.stats()
    assert stats["replica_id"] == "rq"
    assert stats["queue_wait"]["count"] == 1
    assert stats["queue_wait"]["max_s"] == pytest.approx(0.25)


# ---- the full stack: socket front-end + worker + bit-identity ----------


def _worker(qdir, n_requests, replica_id="r0"):
    server = QBAServer(chunk_trials=4, replica_id=replica_id)
    return serve_file_queue(
        server, str(qdir), poll_s=0.01, max_requests=n_requests
    )


def test_socket_frontend_end_to_end_with_admission(tmp_path):
    qdir = tmp_path / "q"
    ac = AdmissionController(chunk_trials=4, replicas=1, window_chunks=64)
    fe = FleetFrontend(str(qdir), ac, poll_s=0.01, max_requests=3)
    worker = threading.Thread(target=_worker, args=(qdir, 2), daemon=True)
    worker.start()
    port = fe.start_in_thread()
    lines = [
        json.dumps(_req("s1", trials=3, seed=5).to_json()),
        json.dumps({"n_parties": 4, "size_l": 4, "trials": 2}),  # no id
        "this is not json",
        json.dumps({"request_id": "bad1", "n_parties": 0, "size_l": 4,
                    "trials": 2}),
    ]
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    for line in lines:
        wire.write(line + "\n")
    wire.flush()
    conn.shutdown(socket.SHUT_WR)
    results = [json.loads(line) for line in wire if line.strip()]
    fe.stop_in_thread()
    worker.join(timeout=120)
    assert len(results) == 4
    by_id = {r["request_id"]: r for r in results}
    # Valid request: served, admitted, attributed.
    assert by_id["s1"]["error"] is None
    assert by_id["s1"]["admission"]["action"] == ADMIT
    assert by_id["s1"]["replica_id"] == "r0"
    # Id-less request: the front-end assigned one.
    assigned = [rid for rid in by_id if rid.startswith("fl")]
    assert len(assigned) == 1 and by_id[assigned[0]]["error"] is None
    # Malformed line: structured error, not a dropped connection.
    assert "<undecoded>" in by_id
    assert by_id["<undecoded>"]["error"]
    # Invalid config: typed admission rejection, never hits the queue.
    assert "invalid_request" in by_id["bad1"]["error"]
    assert by_id["bad1"]["admission"]["reason"] == "invalid_request"
    assert not os.path.exists(os.path.join(str(qdir), "inbox", "bad1.json"))
    # Bit-identity: the served result equals a direct single-process
    # serve_batch of the identical request.
    direct = serve_batch(QBAServer(chunk_trials=4),
                         [_req("s1", trials=3, seed=5)])[0]
    assert by_id["s1"]["success"] == direct.success
    assert by_id["s1"]["successes"] == direct.successes
    # Forwarded results are consumed out of outbox/ (bounded growth; a
    # reused id can't resolve from a stale file) but still feed the
    # fleet summary from consumed/.
    assert os.listdir(qdir / "outbox") == []
    assert len(os.listdir(qdir / "consumed")) == 2  # s1 + assigned id
    assert fleet_summary(str(qdir))["completed"] == 2


def test_frontend_refuses_id_with_leftover_result(tmp_path):
    # A result file already sitting in outbox/ under an incoming id
    # (client id reuse, or a front-end restarted over a live queue dir)
    # must be refused at intake — resolving the new request from the
    # stale payload while the fresh one executes is never acceptable.
    qdir = tmp_path / "q"
    os.makedirs(qdir / "outbox")
    (qdir / "outbox" / "dup1.json").write_text(json.dumps(
        {"request_id": "dup1", "error": None, "success": [True]}
    ))
    fe = FleetFrontend(str(qdir), None, poll_s=0.01)
    port = fe.start_in_thread()
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    wire.write(json.dumps(_req("dup1", trials=2).to_json()) + "\n")
    wire.flush()
    conn.shutdown(socket.SHUT_WR)
    [res] = [json.loads(line) for line in wire if line.strip()]
    fe.stop_in_thread()
    assert res["request_id"] == "dup1"
    assert "already has a result" in res["error"]
    # The stale file was not consumed and nothing hit the queue.
    assert (qdir / "outbox" / "dup1.json").exists()
    assert not os.path.exists(qdir / "inbox" / "dup1.json")


def test_http_get_status_and_post_jsonl(tmp_path):
    qdir = tmp_path / "q"
    fe = FleetFrontend(str(qdir), None, poll_s=0.01, max_requests=1)
    worker = threading.Thread(target=_worker, args=(qdir, 1), daemon=True)
    worker.start()
    port = fe.start_in_thread()

    def _http(raw: bytes) -> tuple[int, bytes]:
        c = socket.create_connection(("127.0.0.1", port), timeout=120)
        c.sendall(raw)
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
        c.close()
        head, _, body = buf.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), body

    code, body = _http(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
    assert code == 200
    status = json.loads(body)
    assert status["requests_seen"] == 0 and status["admission"] is None

    payload = (json.dumps(_req("h1", trials=2, seed=3).to_json()) + "\n").encode()
    code, body = _http(
        b"POST /eval HTTP/1.1\r\nHost: x\r\nContent-Length: "
        + str(len(payload)).encode() + b"\r\n\r\n" + payload
    )
    assert code == 200
    res = json.loads(body.splitlines()[0])
    assert res["request_id"] == "h1" and res["error"] is None
    assert res["replica_id"] == "r0"
    fe.stop_in_thread()
    worker.join(timeout=120)


# ---- fleet summary -----------------------------------------------------


def test_fleet_summary_aggregates_replicas_and_admission(tmp_path):
    qdir = tmp_path / "q"
    outbox = qdir / "outbox"
    os.makedirs(outbox)
    for i, (rid, rep) in enumerate(
        [("a", "r0"), ("b", "r0"), ("c", "r1"), ("d", "r1"), ("e", "r1")]
    ):
        (outbox / f"{rid}.json").write_text(json.dumps({
            "request_id": rid, "error": None, "latency_s": 0.1 * (i + 1),
            "queue_wait_s": 0.01 * i, "replica_id": rep,
        }))
    (outbox / "err.json").write_text(json.dumps({
        "request_id": "err", "error": "boom", "latency_s": None,
    }))
    (qdir / "summary-r0.json").write_text(json.dumps({
        "replica_id": "r0", "completed": 2, "reclaimed": 3, "expired": 0,
    }))
    summary = fleet_summary(
        str(qdir),
        admission_summary={"decisions": 6},
        elapsed_s=30.0,
    )
    assert summary["results"] == 6
    assert summary["completed"] == 5 and summary["errored"] == 1
    assert summary["replicas"]["r0"]["completed"] == 2
    assert summary["replicas"]["r1"]["completed"] == 3
    assert summary["replicas"]["r0"]["exit_summary"]["reclaimed"] == 3
    assert summary["reclaimed"] == 3
    assert summary["latency"]["count"] == 5
    assert summary["latency"]["p50_s"] == pytest.approx(0.3)
    assert summary["queue_wait"]["count"] == 5
    assert summary["requests_per_min"] == pytest.approx(10.0)
    assert summary["admission"] == {"decisions": 6}


def test_spans_from_jsonl_round_trip(tmp_path):
    from qba_tpu.obs.telemetry import SpanRecorder, spans_from_jsonl

    rec = SpanRecorder()
    with rec.span("request", cat="host", request_id="x", replica_id="r0"):
        with rec.span("serve.dispatch"):
            pass
    path = tmp_path / "spans.jsonl"
    rec.write_jsonl(str(path))
    # A replica killed mid-write leaves a torn last line; the merge
    # must skip it, not crash.
    with open(path, "a") as f:
        f.write('{"name": "torn", "t0_s": ')
    spans = spans_from_jsonl(str(path))
    assert [sp.name for sp in spans] == ["request", "serve.dispatch"]
    assert spans[0].args["replica_id"] == "r0"
    assert spans[0].dur == pytest.approx(rec.spans[0].dur)
    assert spans_from_jsonl(str(tmp_path / "missing.jsonl")) == []


# ---- shared-artifact merge (satellite: lockfile + atomic rename) -------


def test_merge_states_unions_and_new_wins():
    from qba_tpu.serve.persist import _merge_states

    meta = {"schema": "s", "jax_version": "j", "backend": "cpu"}
    old = {**meta, "resolve": [[["k1"], "old"], [["k2"], "old"]],
           "variant": [], "probe": {"tiled": [[["t1"], 1]], "rebuild": [],
                                    "fused": [], "mega": []}}
    new = {**meta, "resolve": [[["k2"], "new"], [["k3"], "new"]],
           "variant": [], "probe": {"tiled": [], "rebuild": [],
                                    "fused": [], "mega": []}}
    merged = _merge_states(old, new)
    entries = dict((json.dumps(k), v) for k, v in merged["resolve"])
    assert entries == {'["k1"]': "old", '["k2"]': "new", '["k3"]': "new"}
    assert merged["probe"]["tiled"] == [[["t1"], 1]]
    # Different jax build: no merge — import would reject it anyway.
    stale = {**old, "jax_version": "other"}
    assert _merge_states(stale, new) == new


def test_save_plans_merges_configs_across_writers(tmp_path):
    # Two sequential saves with disjoint config sets model two replicas
    # flushing: the artifact must hold the union, not the last writer.
    from qba_tpu.serve.persist import save_plans, saved_configs

    cache = str(tmp_path / "cache")
    cfg_a = QBAConfig(n_parties=4, size_l=4, trials=1)
    cfg_b = QBAConfig(n_parties=5, size_l=4, trials=1)
    save_plans(cache, [cfg_a])
    path = save_plans(cache, [cfg_b])
    got = {(c.n_parties, c.size_l) for c in saved_configs(path)}
    assert got == {(4, 4), (5, 4)}
    # Idempotent: re-saving the same shapes does not duplicate entries.
    save_plans(cache, [cfg_a, cfg_b])
    assert len(saved_configs(path)) == 2


def test_plans_lock_is_exclusive(tmp_path):
    from qba_tpu.serve.persist import plans_lock

    cache = str(tmp_path / "cache")
    order: list[str] = []

    def hold():
        with plans_lock(cache):
            order.append("t-acquired")
            time.sleep(0.3)
            order.append("t-released")

    t = threading.Thread(target=hold)
    t.start()
    time.sleep(0.1)  # let the thread take the lock first
    with plans_lock(cache):
        order.append("main-acquired")
    t.join()
    assert order == ["t-acquired", "t-released", "main-acquired"]


# ---- pool plumbing (no subprocesses in tier-1) -------------------------


def test_worker_argv_spawns_the_proven_serve_loop(tmp_path):
    pool = ReplicaPool(str(tmp_path / "q"), replicas=2, chunk_trials=16,
                       cache_dir="/c", reclaim_timeout_s=7.0)
    argv = pool.worker_argv("r1")
    # The pool adds no dispatch path of its own: workers run the stock
    # file-queue serve loop (check_fleet proves this statically too).
    assert "serve" in argv and "file-queue" in argv
    assert argv[argv.index("--replica-id") + 1] == "r1"
    assert argv[argv.index("--chunk-trials") + 1] == "16"
    assert argv[argv.index("--reclaim-timeout-s") + 1] == "7.0"
    assert argv[argv.index("--cache-dir") + 1] == "/c"


def test_make_device_env_pins_tpu_chips():
    cpu = make_device_env(3, "cpu")
    assert cpu["JAX_PLATFORMS"] == "cpu"
    # CPU replicas are capped to one intra-op thread (one replica ~=
    # one core) so replica counts mean something on an N-core host.
    assert "intra_op_parallelism_threads=1" in cpu["XLA_FLAGS"]
    env = make_device_env(3, "tpu")
    assert "XLA_FLAGS" not in env
    assert env["TPU_VISIBLE_CHIPS"] == "3"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"


def test_make_device_env_autodetects_tpu_hardware(monkeypatch):
    from qba_tpu.serve.fleet import tpu_present

    # JAX_PLATFORMS is commonly unset on TPU hosts (jax auto-detects):
    # detection via the TPU runtime env vars must still pin chips, or
    # every replica would grab all chips and replicas 2..N die at boot.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    assert tpu_present()
    env = make_device_env(2)
    assert env["TPU_VISIBLE_CHIPS"] == "2"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
    assert "XLA_FLAGS" not in env  # no CPU thread caps on TPU workers
    assert "JAX_PLATFORMS" not in env  # keep jax's own auto-detection
    # An explicit platform always beats detection.
    cpu = make_device_env(2, "cpu")
    assert "TPU_VISIBLE_CHIPS" not in cpu
    assert cpu["JAX_PLATFORMS"] == "cpu"


def test_check_fleet_is_clean_and_catches_violations(tmp_path):
    from qba_tpu.analysis.transfers import check_fleet

    assert check_fleet().findings == []
    # A front half that imports jax or dispatches device work itself
    # must be flagged.
    bad = tmp_path / "fleet"
    os.makedirs(bad)
    (bad / "frontend.py").write_text(
        "import jax\n\ndef f(cfg, keys):\n    return run_trials(cfg, keys)\n"
    )
    (bad / "pool.py").write_text("class ReplicaPool:\n    pass\n")
    report = check_fleet(str(bad))
    checks = {f.check for f in report.findings}
    assert checks == {"fleet-front"}
    messages = " ".join(f.message for f in report.findings)
    assert "imports jax" in messages
    assert "run_trials" in messages
    assert "worker_argv" in messages


@pytest.mark.slow
def test_two_replica_pool_chaos_kill_loses_nothing(tmp_path):
    """The CI fleet job's kill -9 story, in miniature: 2 subprocess
    replicas, one SIGKILLed mid-stream, every request still answered."""
    from qba_tpu.serve.queuefs import drop_request

    qdir = str(tmp_path / "q")
    pool = ReplicaPool(qdir, replicas=2, chunk_trials=4,
                       reclaim_timeout_s=20.0, poll_s=0.02,
                       cache_dir=str(tmp_path / "cache"))
    pool.start()
    reqs = [_req(f"k{i}", trials=3, seed=i) for i in range(8)]
    inbox = os.path.join(qdir, "inbox")
    os.makedirs(inbox, exist_ok=True)
    for r in reqs:
        drop_request(inbox, r.to_json(), r.request_id)
    outbox = os.path.join(qdir, "outbox")
    deadline = time.time() + 300
    killed = False
    while time.time() < deadline:
        done = len(os.listdir(outbox)) if os.path.isdir(outbox) else 0
        if not killed and done >= 2:
            pool.kill(pool.alive()[-1])
            killed = True
        if done >= len(reqs):
            break
        time.sleep(0.1)
    codes = pool.stop()
    assert killed
    results = {
        name[:-5]: json.loads(open(os.path.join(outbox, name)).read())
        for name in os.listdir(outbox)
    }
    assert set(results) == {r.request_id for r in reqs}  # zero lost
    assert all(r["error"] is None for r in results.values())
    assert -9 in codes.values() or any(
        c != 0 for c in codes.values()
    )  # the victim really died
