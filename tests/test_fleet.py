"""Fleet-subsystem tests (docs/SERVING.md "Fleet").

Six contracts:

* **Pricing determinism** — :meth:`Target.planning_trials` is pure
  arithmetic: same target, same price; tighter precision prices more
  trials; the request budget is a hard cap.
* **Admission determinism** — the admit/defer/reject decision sequence
  is a pure function of the request sequence and the settle points: a
  fixed stream replayed through a fresh controller yields the
  bit-identical decision list, with typed reasons.
* **Attribution** — a result served through the file queue carries the
  serving replica's id and its queue wait, in the wire result AND the
  validated manifest, and each replica writes its own exit summary.
* **Fleet bit-identity** — a request answered through the full socket
  front-end + admission + file-queue worker stack equals a direct
  single-process :func:`serve_batch` run trial for trial.
* **Artifact merge** — concurrent-style saves to one ``plans.json``
  union their resolver states and config shapes instead of clobbering
  (the property that makes a shared warm-start artifact safe for N
  replicas).
* **Self-healing** (docs/KNOWN_ISSUES.md KI-9) — workers heartbeat
  their lifecycle phase; the supervisor's phase-aware watchdog kills
  hung workers, releases a dead worker's claim within one poll,
  quarantines a request blamed for ``poison_threshold`` deaths with a
  structured crash report, and benches a crash-looping slot while the
  admission window shrinks to match.
"""

import json
import os
import socket
import threading
import time

import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.serve import EvalRequest, QBAServer, serve_batch
from qba_tpu.serve.fleet import (
    ADMIT,
    DEFER,
    REASONS,
    REJECT,
    AdmissionController,
    FleetFrontend,
    ReplicaPool,
    fleet_summary,
    make_device_env,
)
from qba_tpu.serve.transport import serve_file_queue
from qba_tpu.stats import parse_target


def _req(rid, n=4, L=4, d=0, trials=4, seed=0, **kw):
    return EvalRequest(
        request_id=rid, n_parties=n, size_l=L, n_dishonest=d,
        trials=trials, seed=seed, **kw,
    )


# ---- pricing -----------------------------------------------------------


def test_planning_trials_deterministic_and_budget_capped():
    t = parse_target("decide vs 1/3")
    assert t.planning_trials(10_000) == t.planning_trials(10_000)
    # The Wald bound at the 1/3 boundary with default delta/confidence
    # is a few hundred trials — well under a 10k budget, over a 10-trial
    # one (the budget is a hard cap, and the floor is one trial).
    price = t.planning_trials(10_000)
    assert 10 < price < 10_000
    assert t.planning_trials(10) == 10
    assert t.planning_trials(1) == 1
    with pytest.raises(ValueError):
        t.planning_trials(0)


def test_planning_trials_monotone_in_precision():
    loose = parse_target("decide vs 1/3 +-0.1").planning_trials(10**6)
    tight = parse_target("decide vs 1/3 +-0.02").planning_trials(10**6)
    assert tight > loose
    wide = parse_target("ci_width<=0.1").planning_trials(10**7)
    narrow = parse_target("ci_width<=0.01").planning_trials(10**7)
    assert narrow > wide
    # Higher confidence prices more trials too.
    p95 = parse_target("ci_width<=0.05 @ 95%").planning_trials(10**7)
    p99 = parse_target("ci_width<=0.05 @ 99%").planning_trials(10**7)
    assert p99 > p95


# ---- admission ---------------------------------------------------------


def _decision_stream(ac):
    """A fixed request sequence with a mid-stream settle; returns the
    decision JSON list (capacity 16: chunk_trials=8 * window_chunks=2)."""
    out = [
        ac.try_admit(_req("A", trials=16)),   # admit, fills the window
        ac.try_admit(_req("B", trials=8)),    # defer: window full
        ac.try_admit(_req("C", trials=24)),   # reject: > whole window
        ac.try_admit(_req("bad", n=0)),       # reject: invalid config
    ]
    ac.settle("A", executed_trials=16)
    out.append(ac.try_admit(_req("B", trials=8)))  # now admits
    return [d.to_json() for d in out]


def _controller(**kw):
    kw.setdefault("chunk_trials", 8)
    kw.setdefault("replicas", 1)
    kw.setdefault("window_chunks", 2)
    return AdmissionController(**kw)


def test_admission_decision_sequence_is_deterministic():
    first = _decision_stream(_controller())
    second = _decision_stream(_controller())
    assert first == second  # pure function of stream + settle points
    actions = [(d["action"], d["reason"]) for d in first]
    assert actions == [
        (ADMIT, "capacity_available"),
        (DEFER, "window_full"),
        (REJECT, "oversized_request"),
        (REJECT, "invalid_request"),
        (ADMIT, "capacity_available"),
    ]
    assert all(d["reason"] in REASONS for d in first)
    # The ledger is visible in every decision: A's admit filled the
    # 16-trial window; B's post-settle admit sees it drained.
    assert first[0]["outstanding_trials"] == 16
    assert first[-1]["outstanding_trials"] == 8


def test_admission_mesh_pricing_breaks_the_memory_wall():
    # Round 9: a 65p (w=128) shape the KI-2 model proves cannot fit one
    # emulated chip is REJECTED by a single-chip controller but ADMITTED
    # by one pricing against the (dp=1, tp=8) ring-sharded ceiling —
    # admission and execution agree on what the mesh can hold.
    from qba_tpu.analysis.memory import HBM_RESERVE

    hbm = HBM_RESERVE + (16 << 20)
    big = _req("big", n=65, L=32, d=2, trials=2)
    flat = _controller(chunk_trials=2, hbm_bytes=hbm)
    dec = flat.try_admit(big)
    assert (dec.action, dec.reason) == (REJECT, "unservable_shape")
    assert "one device" in dec.detail

    sharded = _controller(
        chunk_trials=2, hbm_bytes=hbm, mesh_shape=(1, 8), tp_comms="ring"
    )
    dec = sharded.try_admit(_req("big", n=65, L=32, d=2, trials=2))
    assert (dec.action, dec.reason) == (ADMIT, "capacity_available")
    s = sharded.summary()
    assert s["mesh_shape"] == [1, 8]
    assert s["tp_comms"] == "ring"

    # Oversharded even on the mesh: the reject detail names the mesh
    # and comms the shape was priced against, not "one device".
    tiny = _controller(
        chunk_trials=2, hbm_bytes=HBM_RESERVE + (1 << 20),
        mesh_shape=(1, 8), tp_comms="ring",
    )
    dec = tiny.try_admit(_req("big", n=65, L=32, d=2, trials=2))
    assert (dec.action, dec.reason) == (REJECT, "unservable_shape")
    assert "(dp=1, tp=8)" in dec.detail and "ring" in dec.detail


def test_admission_mesh_indivisible_falls_back_to_single_chip():
    # 4 parties -> 3 lieutenants: tp=2 does not divide, so the shape is
    # priced (and run) unsharded — same ceiling as a meshless controller.
    meshed = _controller(chunk_trials=2, mesh_shape=(4, 2))
    flat = _controller(chunk_trials=2)
    for ac in (meshed, flat):
        d = ac.try_admit(_req("odd", n=4, L=4, trials=2))
        assert d.action == ADMIT
    assert meshed._ceilings == flat._ceilings


def test_admission_prices_targets_below_budget():
    ac = _controller(window_chunks=64)
    dec = ac.try_admit(_req("T", trials=4096, target="decide vs 1/3"))
    assert dec.action == ADMIT
    # Chunk-quantized Wald price, not the full 4096-trial budget.
    assert dec.priced_trials % 8 == 0
    assert dec.priced_trials < 4096
    untargeted = ac.try_admit(_req("U", trials=24))
    assert untargeted.priced_trials == 24  # already chunk-aligned


def test_admission_rejects_unservable_shape():
    # With (essentially) no HBM the KI-2 ceiling is below one chunk:
    # the shape can never execute, so it must be rejected up front —
    # not parked in the queue to wedge a replica.
    ac = _controller(hbm_bytes=1)
    dec = ac.try_admit(_req("huge", trials=8))
    assert (dec.action, dec.reason) == (REJECT, "unservable_shape")
    assert ac.outstanding_trials == 0


def test_retry_defers_do_not_inflate_decision_ledger():
    # The front-end's deferred-retry loop re-polls the deferred head on
    # every settle event; those polls run with record=False so the
    # decision list stays a pure function of the request stream and
    # settle points — not of how many settles fired while a request
    # waited.  Only the retry that resolves is recorded, via record().
    ac = _controller()  # capacity 16
    ac.try_admit(_req("A", trials=16))
    assert ac.try_admit(_req("B", trials=8)).action == DEFER
    for _ in range(5):
        dec = ac.try_admit(_req("B", trials=8), record=False)
        assert dec.action == DEFER
    assert len(ac.decisions) == 2  # the admit + the one intake DEFER
    ac.settle("A")
    dec = ac.try_admit(_req("B", trials=8), record=False)
    assert dec.action == ADMIT
    ac.record(dec)
    assert [d.action for d in ac.decisions] == [ADMIT, DEFER, ADMIT]
    assert ac.outstanding_trials == 8  # record=False still prices admits


def test_batch_admission_records_one_defer_per_wait():
    # The atlas campaign driver re-offers its whole ranked frontier
    # queue on every loop sweep (``batch=True``): a request deferred N
    # times across N sweeps lands in the ledger exactly once per
    # *wait*, so the decision list stays a pure function of the
    # request stream + settle points — not of the driver's poll
    # cadence (docs/SERVING.md "Batch admission").
    def stream(ac):
        ac.try_admit(_req("A", trials=16), batch=True)
        for _ in range(5):  # five sweeps re-offer B: one recorded DEFER
            assert ac.try_admit(
                _req("B", trials=8), batch=True).action == DEFER
        assert [d.action for d in ac.decisions] == [ADMIT, DEFER]
        ac.settle("A")
        assert ac.try_admit(
            _req("B", trials=8), batch=True).action == ADMIT
        # a defer AFTER an admit opens a new wait: recorded again
        for _ in range(3):
            assert ac.try_admit(
                _req("C", trials=16), batch=True).action == DEFER
        ac.settle("B")
        assert ac.try_admit(
            _req("C", trials=16), batch=True).action == ADMIT
        return [(d.action, d.request_id) for d in ac.decisions]

    first = stream(_controller())  # capacity 16
    assert first == [
        (ADMIT, "A"), (DEFER, "B"), (ADMIT, "B"), (DEFER, "C"),
        (ADMIT, "C"),
    ]
    assert first == stream(_controller())  # replay: bit-identical


def test_admission_settle_is_idempotent_and_releases():
    ac = _controller()
    ac.try_admit(_req("A", trials=16))
    assert ac.settle("A") == 16
    assert ac.settle("A") == 0  # double-settle releases nothing
    assert ac.settle("never-admitted") == 0
    s = ac.summary()
    assert s["released_trials"] == 16
    assert s["outstanding_trials"] == 0
    assert s["by_action"] == {ADMIT: 1}


# ---- attribution through the file queue --------------------------------


def _queue_dirs(tmp_path):
    qdir = tmp_path / "q"
    for d in ("inbox", "claimed", "done", "dead", "outbox"):
        os.makedirs(qdir / d)
    return qdir


def test_result_and_manifest_carry_replica_and_queue_wait(tmp_path):
    qdir = _queue_dirs(tmp_path)
    req = _req("w0", trials=3, seed=5)
    (qdir / "inbox" / "w0.json").write_text(json.dumps(req.to_json()))
    server = QBAServer(chunk_trials=4, replica_id="r7")
    stats = serve_file_queue(server, str(qdir), poll_s=0.01, max_requests=1)
    res = json.loads((qdir / "outbox" / "w0.json").read_text())
    assert res["error"] is None
    assert res["replica_id"] == "r7"
    assert res["queue_wait_s"] >= 0.0
    # Attribution is in the validated manifest too, not just the wire.
    assert res["manifest"]["replica_id"] == "r7"
    assert res["manifest"]["queue_wait_s"] == res["queue_wait_s"]
    # Per-replica exit summary: summary-<id>.json, never summary.json
    # (N replicas share the queue dir and must not clobber each other).
    assert stats["replica_id"] == "r7"
    assert (qdir / "summary-r7.json").exists()
    assert not (qdir / "summary.json").exists()
    summary = json.loads((qdir / "summary-r7.json").read_text())
    assert summary["replica_id"] == "r7"
    assert summary["queue_wait"]["count"] == 1


def test_queue_wait_summary_in_server_stats():
    server = QBAServer(chunk_trials=4, replica_id="rq")
    server.submit(_req("q0", trials=2), queue_wait_s=0.25)
    server.flush()
    stats = server.stats()
    assert stats["replica_id"] == "rq"
    assert stats["queue_wait"]["count"] == 1
    assert stats["queue_wait"]["max_s"] == pytest.approx(0.25)


# ---- the full stack: socket front-end + worker + bit-identity ----------


def _worker(qdir, n_requests, replica_id="r0"):
    server = QBAServer(chunk_trials=4, replica_id=replica_id)
    return serve_file_queue(
        server, str(qdir), poll_s=0.01, max_requests=n_requests
    )


def test_socket_frontend_end_to_end_with_admission(tmp_path):
    qdir = tmp_path / "q"
    ac = AdmissionController(chunk_trials=4, replicas=1, window_chunks=64)
    fe = FleetFrontend(str(qdir), ac, poll_s=0.01, max_requests=3)
    worker = threading.Thread(target=_worker, args=(qdir, 2), daemon=True)
    worker.start()
    port = fe.start_in_thread()
    lines = [
        json.dumps(_req("s1", trials=3, seed=5).to_json()),
        json.dumps({"n_parties": 4, "size_l": 4, "trials": 2}),  # no id
        "this is not json",
        json.dumps({"request_id": "bad1", "n_parties": 0, "size_l": 4,
                    "trials": 2}),
    ]
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    for line in lines:
        wire.write(line + "\n")
    wire.flush()
    conn.shutdown(socket.SHUT_WR)
    results = [json.loads(line) for line in wire if line.strip()]
    fe.stop_in_thread()
    worker.join(timeout=120)
    assert len(results) == 4
    by_id = {r["request_id"]: r for r in results}
    # Valid request: served, admitted, attributed.
    assert by_id["s1"]["error"] is None
    assert by_id["s1"]["admission"]["action"] == ADMIT
    assert by_id["s1"]["replica_id"] == "r0"
    # Id-less request: the front-end assigned one.
    assigned = [rid for rid in by_id if rid.startswith("fl")]
    assert len(assigned) == 1 and by_id[assigned[0]]["error"] is None
    # Malformed line: structured error, not a dropped connection.
    assert "<undecoded>" in by_id
    assert by_id["<undecoded>"]["error"]
    # Invalid config: typed admission rejection, never hits the queue.
    assert "invalid_request" in by_id["bad1"]["error"]
    assert by_id["bad1"]["admission"]["reason"] == "invalid_request"
    assert not os.path.exists(os.path.join(str(qdir), "inbox", "bad1.json"))
    # Bit-identity: the served result equals a direct single-process
    # serve_batch of the identical request.
    direct = serve_batch(QBAServer(chunk_trials=4),
                         [_req("s1", trials=3, seed=5)])[0]
    assert by_id["s1"]["success"] == direct.success
    assert by_id["s1"]["successes"] == direct.successes
    # Forwarded results are consumed out of outbox/ (bounded growth; a
    # reused id can't resolve from a stale file) but still feed the
    # fleet summary from consumed/.
    assert os.listdir(qdir / "outbox") == []
    assert len(os.listdir(qdir / "consumed")) == 2  # s1 + assigned id
    assert fleet_summary(str(qdir))["completed"] == 2


def test_frontend_refuses_id_with_leftover_result(tmp_path):
    # A result file already sitting in outbox/ under an incoming id
    # (client id reuse, or a front-end restarted over a live queue dir)
    # must be refused at intake — resolving the new request from the
    # stale payload while the fresh one executes is never acceptable.
    qdir = tmp_path / "q"
    os.makedirs(qdir / "outbox")
    (qdir / "outbox" / "dup1.json").write_text(json.dumps(
        {"request_id": "dup1", "error": None, "success": [True]}
    ))
    fe = FleetFrontend(str(qdir), None, poll_s=0.01)
    port = fe.start_in_thread()
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    wire.write(json.dumps(_req("dup1", trials=2).to_json()) + "\n")
    wire.flush()
    conn.shutdown(socket.SHUT_WR)
    [res] = [json.loads(line) for line in wire if line.strip()]
    fe.stop_in_thread()
    assert res["request_id"] == "dup1"
    assert "already has a result" in res["error"]
    # The stale file was not consumed and nothing hit the queue.
    assert (qdir / "outbox" / "dup1.json").exists()
    assert not os.path.exists(qdir / "inbox" / "dup1.json")


def test_http_get_status_and_post_jsonl(tmp_path):
    qdir = tmp_path / "q"
    fe = FleetFrontend(str(qdir), None, poll_s=0.01, max_requests=1)
    worker = threading.Thread(target=_worker, args=(qdir, 1), daemon=True)
    worker.start()
    port = fe.start_in_thread()

    def _http(raw: bytes) -> tuple[int, bytes]:
        c = socket.create_connection(("127.0.0.1", port), timeout=120)
        c.sendall(raw)
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
        c.close()
        head, _, body = buf.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), body

    code, body = _http(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
    assert code == 200
    status = json.loads(body)
    assert status["requests_seen"] == 0 and status["admission"] is None

    payload = (json.dumps(_req("h1", trials=2, seed=3).to_json()) + "\n").encode()
    code, body = _http(
        b"POST /eval HTTP/1.1\r\nHost: x\r\nContent-Length: "
        + str(len(payload)).encode() + b"\r\n\r\n" + payload
    )
    assert code == 200
    res = json.loads(body.splitlines()[0])
    assert res["request_id"] == "h1" and res["error"] is None
    assert res["replica_id"] == "r0"
    fe.stop_in_thread()
    worker.join(timeout=120)


# ---- fleet summary -----------------------------------------------------


def test_fleet_summary_aggregates_replicas_and_admission(tmp_path):
    qdir = tmp_path / "q"
    outbox = qdir / "outbox"
    os.makedirs(outbox)
    for i, (rid, rep) in enumerate(
        [("a", "r0"), ("b", "r0"), ("c", "r1"), ("d", "r1"), ("e", "r1")]
    ):
        (outbox / f"{rid}.json").write_text(json.dumps({
            "request_id": rid, "error": None, "latency_s": 0.1 * (i + 1),
            "queue_wait_s": 0.01 * i, "replica_id": rep,
        }))
    (outbox / "err.json").write_text(json.dumps({
        "request_id": "err", "error": "boom", "latency_s": None,
    }))
    (qdir / "summary-r0.json").write_text(json.dumps({
        "replica_id": "r0", "completed": 2, "reclaimed": 3, "expired": 0,
    }))
    summary = fleet_summary(
        str(qdir),
        admission_summary={"decisions": 6},
        elapsed_s=30.0,
    )
    assert summary["results"] == 6
    assert summary["completed"] == 5 and summary["errored"] == 1
    assert summary["replicas"]["r0"]["completed"] == 2
    assert summary["replicas"]["r1"]["completed"] == 3
    assert summary["replicas"]["r0"]["exit_summary"]["reclaimed"] == 3
    assert summary["reclaimed"] == 3
    assert summary["latency"]["count"] == 5
    assert summary["latency"]["p50_s"] == pytest.approx(0.3)
    assert summary["queue_wait"]["count"] == 5
    assert summary["requests_per_min"] == pytest.approx(10.0)
    assert summary["admission"] == {"decisions": 6}


def test_spans_from_jsonl_round_trip(tmp_path):
    from qba_tpu.obs.telemetry import SpanRecorder, spans_from_jsonl

    rec = SpanRecorder()
    with rec.span("request", cat="host", request_id="x", replica_id="r0"):
        with rec.span("serve.dispatch"):
            pass
    path = tmp_path / "spans.jsonl"
    rec.write_jsonl(str(path))
    # A replica killed mid-write leaves a torn last line; the merge
    # must skip it, not crash.
    with open(path, "a") as f:
        f.write('{"name": "torn", "t0_s": ')
    spans = spans_from_jsonl(str(path))
    assert [sp.name for sp in spans] == ["request", "serve.dispatch"]
    assert spans[0].args["replica_id"] == "r0"
    assert spans[0].dur == pytest.approx(rec.spans[0].dur)
    assert spans_from_jsonl(str(tmp_path / "missing.jsonl")) == []


# ---- shared-artifact merge (satellite: lockfile + atomic rename) -------


def test_merge_states_unions_and_new_wins():
    from qba_tpu.serve.persist import _merge_states

    meta = {"schema": "s", "jax_version": "j", "backend": "cpu"}
    old = {**meta, "resolve": [[["k1"], "old"], [["k2"], "old"]],
           "variant": [], "probe": {"tiled": [[["t1"], 1]], "rebuild": [],
                                    "fused": [], "mega": []}}
    new = {**meta, "resolve": [[["k2"], "new"], [["k3"], "new"]],
           "variant": [], "probe": {"tiled": [], "rebuild": [],
                                    "fused": [], "mega": []}}
    merged = _merge_states(old, new)
    entries = dict((json.dumps(k), v) for k, v in merged["resolve"])
    assert entries == {'["k1"]': "old", '["k2"]': "new", '["k3"]': "new"}
    assert merged["probe"]["tiled"] == [[["t1"], 1]]
    # Different jax build: no merge — import would reject it anyway.
    stale = {**old, "jax_version": "other"}
    assert _merge_states(stale, new) == new


def test_save_plans_merges_configs_across_writers(tmp_path):
    # Two sequential saves with disjoint config sets model two replicas
    # flushing: the artifact must hold the union, not the last writer.
    from qba_tpu.serve.persist import save_plans, saved_configs

    cache = str(tmp_path / "cache")
    cfg_a = QBAConfig(n_parties=4, size_l=4, trials=1)
    cfg_b = QBAConfig(n_parties=5, size_l=4, trials=1)
    save_plans(cache, [cfg_a])
    path = save_plans(cache, [cfg_b])
    got = {(c.n_parties, c.size_l) for c in saved_configs(path)}
    assert got == {(4, 4), (5, 4)}
    # Idempotent: re-saving the same shapes does not duplicate entries.
    save_plans(cache, [cfg_a, cfg_b])
    assert len(saved_configs(path)) == 2


def test_plans_lock_is_exclusive(tmp_path):
    from qba_tpu.serve.persist import plans_lock

    cache = str(tmp_path / "cache")
    order: list[str] = []

    def hold():
        with plans_lock(cache):
            order.append("t-acquired")
            time.sleep(0.3)
            order.append("t-released")

    t = threading.Thread(target=hold)
    t.start()
    time.sleep(0.1)  # let the thread take the lock first
    with plans_lock(cache):
        order.append("main-acquired")
    t.join()
    assert order == ["t-acquired", "t-released", "main-acquired"]


# ---- pool plumbing (no subprocesses in tier-1) -------------------------


def test_worker_argv_spawns_the_proven_serve_loop(tmp_path):
    pool = ReplicaPool(str(tmp_path / "q"), replicas=2, chunk_trials=16,
                       cache_dir="/c", reclaim_timeout_s=7.0)
    argv = pool.worker_argv("r1")
    # The pool adds no dispatch path of its own: workers run the stock
    # file-queue serve loop (check_fleet proves this statically too).
    assert "serve" in argv and "file-queue" in argv
    assert argv[argv.index("--replica-id") + 1] == "r1"
    assert argv[argv.index("--chunk-trials") + 1] == "16"
    assert argv[argv.index("--reclaim-timeout-s") + 1] == "7.0"
    assert argv[argv.index("--cache-dir") + 1] == "/c"


def test_make_device_env_pins_tpu_chips():
    cpu = make_device_env(3, "cpu")
    assert cpu["JAX_PLATFORMS"] == "cpu"
    # CPU replicas are capped to one intra-op thread (one replica ~=
    # one core) so replica counts mean something on an N-core host.
    assert "intra_op_parallelism_threads=1" in cpu["XLA_FLAGS"]
    env = make_device_env(3, "tpu")
    assert "XLA_FLAGS" not in env
    assert env["TPU_VISIBLE_CHIPS"] == "3"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
    assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"


def test_make_device_env_autodetects_tpu_hardware(monkeypatch):
    from qba_tpu.serve.fleet import tpu_present

    # JAX_PLATFORMS is commonly unset on TPU hosts (jax auto-detects):
    # detection via the TPU runtime env vars must still pin chips, or
    # every replica would grab all chips and replicas 2..N die at boot.
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-8")
    assert tpu_present()
    env = make_device_env(2)
    assert env["TPU_VISIBLE_CHIPS"] == "2"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
    assert "XLA_FLAGS" not in env  # no CPU thread caps on TPU workers
    assert "JAX_PLATFORMS" not in env  # keep jax's own auto-detection
    # An explicit platform always beats detection.
    cpu = make_device_env(2, "cpu")
    assert "TPU_VISIBLE_CHIPS" not in cpu
    assert cpu["JAX_PLATFORMS"] == "cpu"


def test_check_fleet_is_clean_and_catches_violations(tmp_path):
    from qba_tpu.analysis.transfers import check_fleet

    assert check_fleet().findings == []
    # A front half that imports jax or dispatches device work itself
    # must be flagged.
    bad = tmp_path / "fleet"
    os.makedirs(bad)
    (bad / "frontend.py").write_text(
        "import jax\n\ndef f(cfg, keys):\n    return run_trials(cfg, keys)\n"
    )
    (bad / "pool.py").write_text("class ReplicaPool:\n    pass\n")
    report = check_fleet(str(bad))
    checks = {f.check for f in report.findings}
    assert checks == {"fleet-front"}
    messages = " ".join(f.message for f in report.findings)
    assert "imports jax" in messages
    assert "run_trials" in messages
    assert "worker_argv" in messages


# ---- self-healing: heartbeats, watchdog, quarantine, breaker -----------


def _write_hb(qdir, rid, pid, phase, monotonic, request_ids=()):
    """Doctor a heartbeat file directly so tests control the stamp."""
    from qba_tpu.serve.queuefs import heartbeat_path, write_json_atomic

    write_json_atomic(heartbeat_path(str(qdir), rid), {
        "schema": "qba-tpu/heartbeat/v1", "replica_id": rid, "pid": pid,
        "seq": 1, "phase": phase, "request_ids": list(request_ids),
        "monotonic": monotonic, "stamp": 0.0,
    })


class _FakeProc:
    def __init__(self, pid, returncode=None):
        self.pid = pid
        self.returncode = returncode

    def poll(self):
        return self.returncode


class _StubReplica:
    def __init__(self, rid, pid, returncode=None):
        self.replica_id = rid
        self.proc = _FakeProc(pid, returncode)
        self.env = {}
        self.returncode = returncode

    @property
    def alive(self):
        return self.proc.returncode is None


class _StubPool:
    """Duck-typed pool: real queue dir, fake processes."""

    def __init__(self, queue_dir, replicas):
        self.queue_dir = str(queue_dir)
        self.replicas = replicas
        self.benched = set()
        self.restarted = []
        self.killed = []

    def kill(self, rid):
        for r in self.replicas:
            if r.replica_id == rid and r.alive:
                self.killed.append(rid)
                r.proc.returncode = -9
                return r.proc.pid
        raise ValueError(rid)

    def bench(self, rid):
        if rid in self.benched:
            return False
        self.benched.add(rid)
        return True

    def respawn_dead(self):
        return []


def test_heartbeat_writer_phases_throttle_and_read(tmp_path):
    from qba_tpu.serve.queuefs import (
        HEARTBEAT_PHASES, HeartbeatWriter, read_heartbeat,
    )

    qdir = str(_queue_dirs(tmp_path))
    hb = HeartbeatWriter(qdir, "r0", idle_rebeat_s=60.0)
    with pytest.raises(ValueError):
        hb.beat("warp")
    assert hb.beat("idle") is True
    payload = read_heartbeat(qdir, "r0")
    assert payload["schema"] == "qba-tpu/heartbeat/v1"
    assert payload["replica_id"] == "r0"
    assert payload["pid"] == os.getpid()
    assert (payload["seq"], payload["phase"]) == (1, "idle")
    assert payload["request_ids"] == []
    assert payload["monotonic"] <= time.monotonic()
    # idle -> idle inside the throttle window: no write, stamp unchanged.
    assert hb.beat("idle") is False
    assert read_heartbeat(qdir, "r0")["seq"] == 1
    # Phase transitions always write, carrying the in-flight ids.
    assert hb.beat("claim", ["w1"]) is True
    assert read_heartbeat(qdir, "r0")["request_ids"] == ["w1"]
    # idle after work always writes too (the throttle is idle->idle).
    assert hb.beat("idle") is True
    assert read_heartbeat(qdir, "r0")["seq"] == 3
    assert read_heartbeat(qdir, "never-booted") is None
    assert set(HEARTBEAT_PHASES) == {
        "idle", "claim", "compile", "dispatch", "readback",
    }
    # A missing queue dir degrades the beat, never the worker.
    gone = HeartbeatWriter(str(tmp_path / "nope" / "q"), "r9")
    assert gone.beat("claim", ["x"]) is False


def test_serve_loop_heartbeats_through_the_phases(tmp_path):
    from qba_tpu.serve.queuefs import read_heartbeat

    qdir = _queue_dirs(tmp_path)
    req = _req("hb0", trials=3, seed=5)
    (qdir / "inbox" / "hb0.json").write_text(json.dumps(req.to_json()))
    server = QBAServer(chunk_trials=4, replica_id="r3")
    serve_file_queue(server, str(qdir), poll_s=0.01, max_requests=1)
    hb = read_heartbeat(str(qdir), "r3")
    # The worker beat at claim, compile/dispatch, and readback at
    # minimum — and every beat came from THIS process (the supervisor
    # matches pids to tell a respawn from its predecessor's stale file).
    assert hb is not None
    assert hb["pid"] == os.getpid()
    assert hb["seq"] >= 3
    assert hb["phase"] in ("idle", "readback")


def test_supervisor_classification_is_phase_aware(tmp_path):
    from qba_tpu.serve.fleet import FleetSupervisor, WATCHDOG_PHASE_SCALE

    qdir = _queue_dirs(tmp_path)
    r0 = _StubReplica("r0", 100)
    pool = _StubPool(qdir, [r0])
    now = [1000.0]
    sup = FleetSupervisor(pool, watchdog_s=10.0, clock=lambda: now[0])
    with pytest.raises(ValueError):
        FleetSupervisor(pool, watchdog_s=0.0)
    with pytest.raises(ValueError):
        FleetSupervisor(pool, poison_threshold=0)
    # No heartbeat yet: booting, healthy inside the grace window
    # (3x watchdog by default), hung beyond it.
    v = sup.classify(r0)
    assert (v["state"], v["phase"]) == ("healthy", "boot")
    now[0] = 1031.0
    assert sup.classify(r0)["state"] == "hung"
    # A stale file from a previous pid is "no beat from THIS process".
    _write_hb(qdir, "r0", pid=999, phase="dispatch", monotonic=1030.0)
    assert sup.classify(r0)["phase"] == "boot"
    # Fresh dispatch beat: busy now, hung once it ages past watchdog_s.
    _write_hb(qdir, "r0", 100, "dispatch", 1031.0, ["w1"])
    now[0] = 1036.0
    v = sup.classify(r0)
    assert (v["state"], v["phase"], v["request_ids"]) == (
        "busy", "dispatch", ["w1"],
    )
    now[0] = 1042.0
    assert sup.classify(r0)["state"] == "hung"
    # The same age in a compile phase is still busy: cold XLA compiles
    # get WATCHDOG_PHASE_SCALE x the base budget.
    _write_hb(qdir, "r0", 100, "compile", 1031.0, ["w1"])
    assert sup.classify(r0)["state"] == "busy"
    now[0] = 1031.0 + 10.0 * WATCHDOG_PHASE_SCALE["compile"] + 1.0
    assert sup.classify(r0)["state"] == "hung"
    # Fresh idle beat: healthy.  Dead process: dead, with exit code.
    _write_hb(qdir, "r0", 100, "idle", now[0])
    assert sup.classify(r0)["state"] == "healthy"
    r0.proc.returncode = -9
    v = sup.classify(r0)
    assert (v["state"], v["exit_code"]) == ("dead", -9)


def test_supervisor_kills_hung_and_fast_releases_claim(tmp_path):
    from qba_tpu.serve.fleet import FleetSupervisor

    qdir = _queue_dirs(tmp_path)
    (qdir / "claimed" / "w1.json").write_text(
        json.dumps(_req("w1", trials=3).to_json())
    )
    r0 = _StubReplica("r0", 100)
    pool = _StubPool(qdir, [r0, _StubReplica("r1", 101)])
    now = [1000.0]
    sup = FleetSupervisor(pool, watchdog_s=5.0, clock=lambda: now[0])
    _write_hb(qdir, "r0", 100, "dispatch", 1000.0, ["w1"])
    _write_hb(qdir, "r1", 101, "idle", 1000.0)
    health = sup.health()
    assert health["r0"]["state"] == "busy"
    assert health["r1"] == {**health["r1"], "state": "healthy",
                            "benched": False}
    now[0] = 1006.0  # r0's beat is now stale; r1 is merely idle-aged
    _write_hb(qdir, "r1", 101, "idle", 1005.5)
    step = sup.poll()
    # The wedged worker was killed and its death blamed on w1 (one
    # blame < threshold), so the claim went straight back to the inbox
    # — one supervisor poll, not one reclaim timeout.
    assert step["hung_killed"] == ["r0"] and pool.killed == ["r0"]
    assert [d["replica_id"] for d in step["deaths"]] == ["r0"]
    assert (qdir / "inbox" / "w1.json").exists()
    assert not (qdir / "claimed" / "w1.json").exists()
    assert sup.ledger["w1"]["releases"] == 1
    assert not sup.ledger["w1"]["quarantined"]
    assert len(sup.hung_killed) == 1
    ledger = json.loads((qdir / "crash_ledger.json").read_text())
    assert ledger["schema"] == "qba-tpu/crash-ledger/v1"
    assert "w1" in ledger["blame"] and len(ledger["deaths"]) == 1


def test_supervisor_quarantines_poison_with_crash_report(tmp_path):
    from qba_tpu.serve.fleet import FleetSupervisor

    qdir = _queue_dirs(tmp_path)
    (qdir / "claimed" / "p1.json").write_text(
        json.dumps(_req("p1", trials=3).to_json())
    )
    r0 = _StubReplica("r0", 100, returncode=113)
    r1 = _StubReplica("r1", 101)
    ridle = _StubReplica("r2", 102, returncode=-9)
    pool = _StubPool(qdir, [r0, r1, ridle])
    now = [1000.0]
    sup = FleetSupervisor(pool, watchdog_s=30.0, poison_threshold=2,
                          clock=lambda: now[0])
    _write_hb(qdir, "r0", 100, "dispatch", 1000.0, ["p1"])
    _write_hb(qdir, "r1", 101, "idle", 1000.0)
    # An idle death blames nobody — there was nothing in flight.
    _write_hb(qdir, "r2", 102, "idle", 1000.0)
    sup.poll()
    assert sup.ledger["p1"]["releases"] == 1
    assert (qdir / "inbox" / "p1.json").exists()
    assert "r2" not in [
        d["replica_id"] for e in sup.ledger.values() for d in e["deaths"]
    ]
    # The released claim kills its second worker: threshold reached.
    r1.proc.returncode = 113
    _write_hb(qdir, "r1", 101, "claim", 1001.0, ["p1"])
    sup.poll()
    entry = sup.ledger["p1"]
    assert entry["quarantined"] and len(entry["deaths"]) == 2
    # Dead-lettered NOW — not after the reclaim ladder.
    assert (qdir / "dead" / "p1.json").exists()
    assert not (qdir / "inbox" / "p1.json").exists()
    res = json.loads((qdir / "outbox" / "p1.json").read_text())
    assert "quarantined as poison" in res["error"]
    report = res["crash_report"]
    assert set(report) == {
        "blamed_replicas", "phases", "exit_codes", "reclaim_count",
        "flight_recorder",
    }
    assert report["blamed_replicas"] == ["r0", "r1"]
    assert report["phases"] == ["dispatch", "claim"]
    assert report["exit_codes"] == [113, 113]
    assert report["reclaim_count"] == 1
    # Blast radius: the poison request cost exactly 2 workers.
    assert len(report["blamed_replicas"]) == sup.poison_threshold
    # The fleet summary totals the quarantine from the wire result AND
    # the on-disk ledger, plus the supervisor's own self_healing block.
    summary = fleet_summary(str(qdir), self_healing=sup.summary())
    assert summary["quarantined"] == 1
    assert summary["crash_reports"]["p1"] == report
    assert summary["crash_ledger"]["blamed_requests"] == 1
    assert summary["crash_ledger"]["quarantined"] == 1
    assert summary["crash_ledger"]["deaths"] == 3
    assert summary["self_healing"]["quarantined"]["p1"]["request_id"] == "p1"
    assert summary["self_healing"]["releases"] == 1


def test_breaker_benches_slot_and_releases_admission_capacity(tmp_path):
    from qba_tpu.serve.fleet import FleetSupervisor

    qdir = _queue_dirs(tmp_path)
    r0 = _StubReplica("r0", 100, returncode=-9)
    pool = _StubPool(qdir, [r0, _StubReplica("r1", 101)])
    ac = _controller(replicas=2)  # capacity 2 * 2 * 8 = 32
    now = [1000.0]
    sup = FleetSupervisor(pool, admission=ac, watchdog_s=30.0,
                          breaker_k=2, breaker_window_s=60.0,
                          clock=lambda: now[0])
    sup.poll()
    assert pool.benched == set()  # one death is not a crash loop
    # The slot's respawn dies too, inside the breaker window.
    r0.proc = _FakeProc(102, returncode=-9)
    now[0] = 1010.0
    step = sup.poll()
    assert step["benched"] == ["r0"]
    assert pool.benched == {"r0"}
    # Admission released the benched slot's share of the window...
    assert ac.capacity_trials == 16
    s = ac.summary()
    assert s["base_capacity_trials"] == 32
    assert s["benched_replicas"] == ["r0"]
    # ...exactly once: further deaths of a benched slot are no-ops.
    assert ac.bench_replica("r0") == 0
    assert ac.capacity_trials == 16
    assert sup.bench_events[0]["capacity_released"] == 16
    assert sup.summary()["benched"] == ["r0"]
    # Bench state is visible in /status health.
    assert sup.health()["r0"]["benched"] is True


def test_respawn_backoff_and_max_respawns_bench(tmp_path, monkeypatch):
    qdir = _queue_dirs(tmp_path)
    pool = ReplicaPool(str(qdir), replicas=1, max_respawns=2,
                       respawn_backoff_s=60.0)
    spawned = []

    def fake_spawn(index):
        r = _StubReplica(f"r{index}", 200 + len(spawned))
        spawned.append(r)
        return r

    monkeypatch.setattr(pool, "_spawn", fake_spawn)
    pool.replicas = [_StubReplica("r0", 100, returncode=-9)]
    t0 = time.time()
    assert pool.respawn_dead() == ["r0"]
    assert len(spawned) == 1
    [entry] = pool.restarted
    assert entry["replica_id"] == "r0" and entry["respawns"] == 1
    assert t0 <= entry["at"] <= time.time()  # timestamped audit trail
    # The respawn dies immediately: the backoff gate holds the slot.
    spawned[-1].proc.returncode = -9
    assert pool.respawn_dead() == []
    assert len(spawned) == 1
    # Past the gate it respawns again — then hits max_respawns and is
    # benched for good instead of becoming a hot respawn loop.
    pool._next_respawn_at["r0"] = 0.0
    assert pool.respawn_dead() == ["r0"]
    spawned[-1].proc.returncode = -9
    pool._next_respawn_at["r0"] = 0.0
    assert pool.respawn_dead() == []
    assert pool.benched == {"r0"}
    assert [e["respawns"] for e in pool.restarted] == [1, 2]
    state = json.loads((qdir / "replicas.json").read_text())
    assert state["benched"] == ["r0"]
    assert len(state["restarted"]) == 2


def test_pool_kill_and_stop_survive_wedged_process(tmp_path):
    import subprocess

    class _WedgedProc:
        pid = 4242
        returncode = None

        def poll(self):
            return None

        def send_signal(self, sig):
            pass

        def kill(self):
            pass

        def wait(self, timeout=None):
            raise subprocess.TimeoutExpired(cmd="worker", timeout=timeout)

    qdir = _queue_dirs(tmp_path)
    pool = ReplicaPool(str(qdir), replicas=1)
    stub = _StubReplica("r0", 4242)
    stub.proc = _WedgedProc()
    pool.replicas = [stub]
    # A zombie stuck in an uninterruptible wait must not raise out of
    # the chaos/supervisor kill path nor wedge pool shutdown.
    assert pool.kill("r0") == 4242
    codes = pool.stop(timeout_s=0.2)
    assert codes == {"r0": None}


def test_expired_request_releases_admission_capacity(tmp_path):
    # Satellite of KI-9: a deadline-expired request comes back as an
    # error result, and forwarding it must settle its priced capacity —
    # otherwise expiries leak the admission window shut.
    qdir = tmp_path / "q"
    ac = AdmissionController(chunk_trials=4, replicas=1, window_chunks=2)
    fe = FleetFrontend(str(qdir), ac, poll_s=0.01, max_requests=1)
    worker = threading.Thread(target=_worker, args=(qdir, 1), daemon=True)
    worker.start()
    port = fe.start_in_thread()
    req = _req("exp1", trials=8, seed=2, deadline_s=0.001)
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    wire.write(json.dumps(req.to_json()) + "\n")
    wire.flush()
    conn.shutdown(socket.SHUT_WR)
    [res] = [json.loads(line) for line in wire if line.strip()]
    fe.stop_in_thread()
    worker.join(timeout=120)
    assert res["admission"]["action"] == ADMIT
    assert res["admission"]["priced_trials"] == 8
    assert res["error"] and "deadline exceeded" in res["error"]
    # The expiry settled: nothing outstanding, the full price released.
    assert ac.outstanding_trials == 0
    assert ac.summary()["released_trials"] == 8
    assert ac.summary()["outstanding_trials"] == 0


@pytest.mark.slow
def test_supervised_pool_quarantines_poison(tmp_path, monkeypatch):
    """The CI chaos-poison story in miniature: a request that kills its
    worker is dead-lettered with a crash report after exactly 2 deaths,
    and every other request is still answered."""
    from qba_tpu.serve.fleet import FleetSupervisor
    from qba_tpu.serve.queuefs import drop_request
    from qba_tpu.serve.transport import CRASH_HOOK_ENV, CRASH_HOOK_EXIT

    # The hook must stay set for the whole run: supervisor respawns
    # inherit it, and a respawn must be just as mortal.
    monkeypatch.setenv(CRASH_HOOK_ENV, "poison")
    qdir = str(tmp_path / "q")
    pool = ReplicaPool(qdir, replicas=2, chunk_trials=4,
                       reclaim_timeout_s=120.0, poll_s=0.02,
                       respawn_backoff_s=0.2,
                       cache_dir=str(tmp_path / "cache"))
    sup = FleetSupervisor(pool, watchdog_s=30.0, poison_threshold=2)
    pool.start()
    stop = threading.Event()
    thread = threading.Thread(target=sup.run, args=(stop, 0.1), daemon=True)
    thread.start()
    reqs = [_req(f"g{i}", trials=3, seed=i) for i in range(5)]
    reqs.insert(2, _req("x-poison-x", trials=3, seed=9))
    inbox = os.path.join(qdir, "inbox")
    os.makedirs(inbox, exist_ok=True)
    for r in reqs:
        drop_request(inbox, r.to_json(), r.request_id)
    outbox = os.path.join(qdir, "outbox")
    deadline = time.time() + 540
    while time.time() < deadline:
        done = len(os.listdir(outbox)) if os.path.isdir(outbox) else 0
        if done >= len(reqs):
            break
        time.sleep(0.2)
    stop.set()
    thread.join(timeout=30)
    pool.stop()
    results = {
        name[:-5]: json.loads(open(os.path.join(outbox, name)).read())
        for name in os.listdir(outbox)
    }
    assert set(results) == {r.request_id for r in reqs}  # zero lost
    poison = results.pop("x-poison-x")
    assert "quarantined as poison" in poison["error"]
    report = poison["crash_report"]
    assert set(report) == {
        "blamed_replicas", "phases", "exit_codes", "reclaim_count",
    }
    # Bounded blast radius: exactly poison_threshold workers died for
    # it (the reclaim ladder never got a turn), and the hook's exit
    # code is what the supervisor recorded.
    assert len(report["blamed_replicas"]) == 2
    assert all(c == CRASH_HOOK_EXIT for c in report["exit_codes"])
    assert all(r["error"] is None for r in results.values())
    assert sup.summary()["deaths"] >= 2
    summary = fleet_summary(qdir, self_healing=sup.summary())
    assert summary["quarantined"] == 1
    assert summary["crash_ledger"]["quarantined"] == 1


@pytest.mark.slow
def test_supervisor_watchdog_recovers_sigstop(tmp_path):
    """A SIGSTOP'd worker never exits and never beats: only the
    watchdog can catch it.  The frozen worker must be detected and
    SIGKILLed off a stale beat, and the stream must still finish with
    zero lost requests.

    The victim is frozen once its heartbeat says ``idle`` — freezing
    mid-compile would lawfully take 30x the watchdog budget to detect
    (WATCHDOG_PHASE_SCALE), turning the test into a slow-clock test of
    the wrong thing — and the wait loop requires BOTH stream
    completion and the watchdog kill: a fast survivor finishing the
    stream first must not let the test skip the detection proof."""
    import signal as _signal

    from qba_tpu.serve.fleet import FleetSupervisor
    from qba_tpu.serve.queuefs import drop_request, read_heartbeat

    qdir = str(tmp_path / "q")
    pool = ReplicaPool(qdir, replicas=2, chunk_trials=4,
                       reclaim_timeout_s=300.0, poll_s=0.02,
                       respawn_backoff_s=0.2,
                       cache_dir=str(tmp_path / "cache"))
    sup = FleetSupervisor(pool, watchdog_s=5.0)
    pool.start()
    stop = threading.Event()
    thread = threading.Thread(target=sup.run, args=(stop, 0.1), daemon=True)
    thread.start()
    reqs = [_req(f"h{i}", trials=3, seed=i) for i in range(8)]
    inbox = os.path.join(qdir, "inbox")
    os.makedirs(inbox, exist_ok=True)
    for r in reqs:
        drop_request(inbox, r.to_json(), r.request_id)
    outbox = os.path.join(qdir, "outbox")
    deadline = time.time() + 540
    victim = pool.replicas[-1].replica_id
    victim_pid = pool.replicas[-1].proc.pid
    stopped = False
    while time.time() < deadline:
        if not stopped:
            hb = read_heartbeat(qdir, victim)
            if (
                hb is not None
                and hb.get("pid") == victim_pid
                and hb.get("phase") == "idle"
            ):
                os.kill(victim_pid, _signal.SIGSTOP)
                stopped = True
        done = len(os.listdir(outbox)) if os.path.isdir(outbox) else 0
        if done >= len(reqs) and stopped and sup.hung_killed:
            break
        time.sleep(0.2)
    stop.set()
    thread.join(timeout=30)
    pool.stop()
    assert stopped
    # The watchdog caught the frozen worker off its stale idle beat.
    [kill] = sup.hung_killed[:1]
    assert kill["replica_id"] == victim and kill["pid"] == victim_pid
    assert kill["beat_age_s"] >= 5.0
    results = {
        name[:-5]: json.loads(open(os.path.join(outbox, name)).read())
        for name in os.listdir(outbox)
    }
    assert set(results) == {r.request_id for r in reqs}  # zero lost
    assert all(r["error"] is None for r in results.values())


@pytest.mark.slow
def test_two_replica_pool_chaos_kill_loses_nothing(tmp_path):
    """The CI fleet job's kill -9 story, in miniature: 2 subprocess
    replicas, one SIGKILLed mid-stream, every request still answered."""
    from qba_tpu.serve.queuefs import drop_request

    qdir = str(tmp_path / "q")
    pool = ReplicaPool(qdir, replicas=2, chunk_trials=4,
                       reclaim_timeout_s=20.0, poll_s=0.02,
                       cache_dir=str(tmp_path / "cache"))
    pool.start()
    reqs = [_req(f"k{i}", trials=3, seed=i) for i in range(8)]
    inbox = os.path.join(qdir, "inbox")
    os.makedirs(inbox, exist_ok=True)
    for r in reqs:
        drop_request(inbox, r.to_json(), r.request_id)
    outbox = os.path.join(qdir, "outbox")
    deadline = time.time() + 300
    killed = False
    while time.time() < deadline:
        done = len(os.listdir(outbox)) if os.path.isdir(outbox) else 0
        if not killed and done >= 2:
            pool.kill(pool.alive()[-1])
            killed = True
        if done >= len(reqs):
            break
        time.sleep(0.1)
    codes = pool.stop()
    assert killed
    results = {
        name[:-5]: json.loads(open(os.path.join(outbox, name)).read())
        for name in os.listdir(outbox)
    }
    assert set(results) == {r.request_id for r in reqs}  # zero lost
    assert all(r["error"] is None for r in results.values())
    assert -9 in codes.values() or any(
        c != 0 for c in codes.values()
    )  # the victim really died
