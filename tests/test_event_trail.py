"""Protocol event trail (VERDICT r1 #3).

The reference logs every protocol event via ``mpi_print``:

* ``tfg.py:124``      — per-rank dishonesty announcement
* ``tfg.py:159-162``  — received particle lists (commander: L1 + Lc)
* ``tfg.py:328-330``  — commander state (isQCorr, chosen order)
* ``tfg.py:169-181``  — commander equivocation (two orders)
* ``tfg.py:203,229``  — every PvL packet send
* ``tfg.py:190``      — step 3a receive + accept
* ``tfg.py:275-284``  — every dishonest action ("The action for general N")
* ``tfg.py:294``      — acceptance verdicts (implicit in Vi growth)
* ``tfg.py:360-363``  — the Decisions / Dishonests / Success verdict

These tests pin the structured-event grammar that replaces that trail:
every reference log class must appear as a (phase, message) pair, and the
acceptance reasons must come from the documented vocabulary.
"""

import json

import jax
import pytest

from qba_tpu.backends.jax_backend import trial_keys
from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.config import QBAConfig
from qba_tpu.obs import EventLog, Level


def _trail(cfg, key):
    log = EventLog(min_level=Level.DEBUG)
    res = run_trial_local(cfg, key, log=log, trial=0)
    return log, res


def _find_key(cfg, pred, limit=64):
    """First trial key whose honesty assignment satisfies ``pred``."""
    from qba_tpu.adversary import assign_dishonest

    keys = trial_keys(cfg)
    for i in range(min(limit, cfg.trials)):
        k_dis = jax.random.split(keys[i], 4)[0]
        import numpy as np

        honest = np.asarray(assign_dishonest(cfg, k_dis))
        if pred(honest):
            return keys[i]
    pytest.skip("no key with the wanted honesty pattern in the scan window")


class TestEventGrammar:
    def test_faulty_run_covers_every_reference_log_class(self):
        # Dishonest lieutenants but honest commander: every log class
        # except equivocation must appear.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=64)
        key = _find_key(cfg, lambda h: h[1] and (~h[2:]).any())
        log, _ = _trail(cfg, key)
        got = {(e.phase, e.message) for e in log.events}
        expected = {
            ("dishonesty", "party role"),  # tfg.py:124
            ("particles", "list received"),  # tfg.py:159-162
            ("step2", "commander order"),  # tfg.py:328-330
            ("step2", "send"),  # tfg.py:203
            ("step3a", "receive"),  # tfg.py:190
            ("round", "attack"),  # tfg.py:275-284
            ("round", "receive"),  # tfg.py:294 verdicts
            ("round", "vi"),  # Vi growth per round
            ("decision", "verdict"),  # tfg.py:360-363
        }
        missing = expected - got
        assert not missing, f"missing event classes: {missing}"

    def test_rebroadcast_send_appears_in_honest_run(self):
        # All-honest: every lieutenant accepts in step 3a and rebroadcasts
        # in round 1 (tfg.py:229).
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=1, trials=64)
        key = _find_key(cfg, lambda h: h.all() or h[2:].all())
        log, _ = _trail(cfg, key)
        assert ("round", "send") in {(e.phase, e.message) for e in log.events}

    def test_equivocation_logged_for_dishonest_commander(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=64)
        key = _find_key(cfg, lambda h: not h[1])
        log, _ = _trail(cfg, key)
        got = {(e.phase, e.message) for e in log.events}
        assert ("step2", "commander equivocates") in got

    def test_reason_vocabulary(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=8)
        allowed = {"accepted", "inconsistent", "duplicate-v",
                   "wrong-evidence-len"}
        for key in trial_keys(cfg):
            log, _ = _trail(cfg, key)
            for e in log.events:
                if "reason" in e.fields:
                    assert e.fields["reason"] in allowed

    def test_verdict_event_matches_result(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=4)
        for key in trial_keys(cfg):
            log, res = _trail(cfg, key)
            verdicts = [e for e in log.events if e.message == "verdict"]
            assert len(verdicts) == 1
            v = verdicts[0].fields
            assert v["success"] == res["success"]
            assert v["decisions"] == res["decisions"]

    def test_trail_off_by_default(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=1)
        # No log argument: must not require one (bench path stays clean).
        res = run_trial_local(cfg, trial_keys(cfg)[0])
        assert "success" in res


class TestEffectTable:
    def test_effect_table_shared_across_backends(self):
        # One attack-edit vocabulary, everywhere: the jax-free
        # multiprocessing party mirrors the adversary table verbatim,
        # and the local/native trail renderers ARE the shared function.
        from qba_tpu import adversary
        from qba_tpu.backends import local_backend, mp_party, native_backend

        assert mp_party._EFFECTS == adversary.EFFECT_NAMES
        assert local_backend.effect_names is adversary.effect_names
        assert native_backend.effect_names is adversary.effect_names
        for bits in range(32):  # every combination of the 5 edit bits
            assert mp_party._effect_names(bits) == adversary.effect_names(
                bits
            ), bits

    def test_effect_table_covers_every_strategy_edit(self):
        from qba_tpu.adversary import (
            CLEAR_L_BIT,
            CLEAR_P_BIT,
            DROP_BIT,
            EFFECT_NAMES,
            FORGE_BIT,
            FORGE_P_BIT,
            effect_names,
        )

        assert [b for b, _ in EFFECT_NAMES] == [
            DROP_BIT, FORGE_BIT, CLEAR_P_BIT, CLEAR_L_BIT, FORGE_P_BIT,
        ]
        assert effect_names(FORGE_P_BIT | FORGE_BIT) == "corrupt-v+forge-P"
        assert effect_names(0) == "none"

    def test_split_trail_renders_forge_p(self):
        # The split strategy's signature edit must surface in the local
        # backend's event trail under its table name.
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, trials=64,
            strategy="split",
        )
        key = _find_key(cfg, lambda h: (~h[2:]).any())
        log, _ = _trail(cfg, key)
        actions = {
            e.fields.get("action")
            for e in log.events
            if e.phase == "round" and e.message == "attack"
        }
        assert any("forge-P" in a for a in actions if a), actions


class TestCLITrail:
    def test_run_verbose_local_prints_trail_and_jsonl(self, tmp_path):
        from qba_tpu.cli import main
        import io

        out = io.StringIO()
        jsonl = tmp_path / "trail.jsonl"
        rc = main(
            [
                "run", "--backend", "local", "--n-parties", "3",
                "--size-l", "8", "--n-dishonest", "1", "--trials", "1",
                "-v", "--jsonl", str(jsonl),
            ],
            out=out,
        )
        assert rc == 0
        text = out.getvalue()
        assert "[step2] commander order" in text
        assert "[decision] verdict" in text
        lines = jsonl.read_text().strip().splitlines()
        events = [json.loads(ln) for ln in lines]
        phases = {e["phase"] for e in events}
        assert {"dishonesty", "particles", "step2", "decision"} <= phases
