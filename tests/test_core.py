"""Unit tests for the pure protocol kernel against a set-based oracle.

The oracle functions below re-state the reference's semantics
(``tfg.py:87-98,128-129,303-306,359-363``) over Python sets, and the
fixed-shape kernel is checked against them on randomized inputs.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.core import (
    Evidence,
    append_own,
    consistent,
    decide_order,
    empty_evidence,
    measure_to_ints,
    success_oracle,
)


def oracle_consistent(v, L, w):
    """Set-of-tuples restatement of ``consistent`` (``tfg.py:87-98``)."""
    if not L:
        return True
    lens = {len(t) for t in L}
    if len(lens) != 1:
        return False
    if not all(0 <= x <= w and x != v for t in L for x in t):
        return False
    the_len = next(iter(lens))
    for a, b in itertools.combinations(L, 2):
        if any(a[k] == b[k] for k in range(the_len)):
            return False
    return True


def evidence_from_tuples(tuples, max_l, size_l):
    """Build an Evidence from a list of tuples (compacted tuple-order form)."""
    ev = empty_evidence(max_l, size_l)
    vals = np.array(ev.vals)
    lens = np.array(ev.lens)
    for i, tv in enumerate(tuples):
        vals[i, : len(tv)] = tv
        lens[i] = len(tv)
    return Evidence(
        vals=jnp.asarray(vals),
        lens=jnp.asarray(lens),
        count=jnp.asarray(len(tuples), dtype=jnp.int32),
    )


class TestConsistent:
    W, SIZE_L, MAX_L = 4, 8, 4

    def check(self, v, rows):
        """rows: list of value-tuples (as the reference's set of tuples)."""
        ev = evidence_from_tuples(rows, self.MAX_L, self.SIZE_L)
        got = bool(consistent(jnp.asarray(v), ev, self.W))
        want = oracle_consistent(v, set(rows), self.W)
        assert got == want, f"v={v} rows={rows}: got {got}, want {want}"

    def test_empty_is_consistent(self):
        self.check(2, [])

    def test_single_row_ok(self):
        self.check(1, [(2, 3, 0)])

    def test_contains_v_fails(self):
        self.check(3, [(2, 3, 0)])

    def test_length_mismatch_fails(self):
        self.check(1, [(2, 3, 0), (3, 2)])

    def test_pairwise_collision_fails(self):
        self.check(1, [(2, 3, 0), (2, 0, 3)])

    def test_pairwise_distinct_ok(self):
        self.check(1, [(2, 3, 0), (3, 0, 2)])

    def test_empty_tuples_vacuous(self):
        # clear-P attack endpoint: L = {()} is consistent (tfg.py:281 case)
        self.check(1, [()])

    def test_collision_at_tuple_index_from_different_p(self):
        # Rows built from *different* P masks but equal length: the
        # reference compares by tuple index (tfg.py:96-98) -> collision.
        self.check(1, [(2, 3), (2, 0)])

    def test_negative_value_fails(self):
        # reference cond 2 lower bound: 0 <= x (tfg.py:94)
        self.check(1, [(2, -2)])

    def test_randomized_against_oracle(self):
        rng = np.random.default_rng(0)
        for _ in range(300):
            n_rows = int(rng.integers(1, self.MAX_L + 1))
            the_len = int(rng.integers(0, 5))
            rows, seen = [], set()
            for _ in range(n_rows):
                # occasional length mutation + out-of-range values to hit
                # conditions 1 and 2, not just 3
                ln = the_len if rng.random() < 0.8 else int(rng.integers(0, 5))
                tv = tuple(
                    int(x) for x in rng.integers(-1 if ln else 0, self.W + 2, ln)
                )
                if -1 in tv:
                    continue  # -1 not representable (docs/DIVERGENCES.md D4)
                if tv not in seen:  # set semantics
                    seen.add(tv)
                    rows.append(tv)
            self.check(int(rng.integers(0, self.W)), rows)


class TestAppendOwn:
    def test_append_and_dedup(self):
        size_l, max_l = 6, 3
        ev = empty_evidence(max_l, size_l)
        p = jnp.asarray([True, False, True, False, False, False])
        li = jnp.asarray([1, 9, 2, 9, 9, 9], dtype=jnp.int32)
        ev = append_own(ev, p, li)
        assert int(ev.count) == 1
        assert int(ev.lens[0]) == 2
        # position-expanded row: values at positions {0, 2}, sentinel
        # elsewhere (docs/DIVERGENCES.md D10)
        assert ev.vals[0].tolist() == [1, -1, 2, -1, -1, -1]
        # identical append is a no-op (set semantics, tfg.py:291)
        ev = append_own(ev, p, li)
        assert int(ev.count) == 1
        # different values -> second row
        li2 = jnp.asarray([3, 9, 0, 9, 9, 9], dtype=jnp.int32)
        ev = append_own(ev, p, li2)
        assert int(ev.count) == 2
        assert int(ev.lens[1]) == 2

    def test_empty_p_appends_empty_tuple(self):
        ev = empty_evidence(2, 4)
        p = jnp.zeros(4, dtype=bool)
        li = jnp.asarray([1, 2, 3, 0], dtype=jnp.int32)
        ev = append_own(ev, p, li)
        assert int(ev.count) == 1 and int(ev.lens[0]) == 0
        ev = append_own(ev, p, li)  # () deduped
        assert int(ev.count) == 1


class TestDecode:
    def test_matches_reference_semantics(self):
        # oracle: int("".join(bits), 2) per group (tfg.py:129)
        rng = np.random.default_rng(1)
        size_l, n_qubits = 5, 3
        raw = rng.integers(0, 2, size_l * n_qubits)
        want = [
            int("".join(str(x) for x in raw[i * n_qubits : (i + 1) * n_qubits]), 2)
            for i in range(size_l)
        ]
        got = measure_to_ints(jnp.asarray(raw), size_l, n_qubits)
        assert got.tolist() == want

    def test_batched(self):
        raw = jnp.asarray([[0, 1, 1, 0], [1, 1, 0, 1]])
        got = measure_to_ints(raw, 2, 2)
        assert got.tolist() == [[1, 2], [3, 1]]


class TestDecide:
    def test_min_of_vi(self):
        vi = jnp.asarray([False, False, True, True])
        assert int(decide_order(vi, jnp.asarray(0), jnp.asarray(False), 4)) == 2

    def test_commander_returns_own_v(self):
        # tfg.py:303-305: the commander decides v regardless of Vi
        vi = jnp.asarray([False, True, False, False])
        assert int(decide_order(vi, jnp.asarray(3), jnp.asarray(True), 4)) == 3

    def test_empty_vi_sentinel(self):
        # divergence D2: reference raises ValueError (tfg.py:306)
        vi = jnp.zeros(4, dtype=bool)
        assert int(decide_order(vi, jnp.asarray(0), jnp.asarray(False), 4)) == 4


class TestOracle:
    def test_unanimous_honest(self):
        d = jnp.asarray([3, 3, 3])
        h = jnp.asarray([True, True, True])
        assert bool(success_oracle(d, h))

    def test_dishonest_excluded(self):
        d = jnp.asarray([3, 3, 0])
        h = jnp.asarray([True, True, False])
        assert bool(success_oracle(d, h))

    def test_disagreement_fails(self):
        d = jnp.asarray([3, 0, 3])
        h = jnp.asarray([True, True, True])
        assert not bool(success_oracle(d, h))

    def test_all_dishonest_fails(self):
        d = jnp.asarray([1, 1])
        h = jnp.asarray([False, False])
        assert not bool(success_oracle(d, h))


class TestConfig:
    def test_derived_params_match_logs(self):
        # w = 4 for 3 parties (log_3.txt:2), w = 16 for 11 (log_11.txt:10)
        assert QBAConfig(n_parties=3, size_l=4).w == 4
        assert QBAConfig(n_parties=11, size_l=4).w == 16
        assert QBAConfig(n_parties=11, size_l=4).n_qubits == 4
        assert QBAConfig(n_parties=11, size_l=4).total_qubits == 48

    def test_validation(self):
        with pytest.raises(ValueError):
            QBAConfig(n_parties=3, size_l=4, n_dishonest=7)
        with pytest.raises(ValueError):
            QBAConfig(n_parties=1, size_l=4)
        with pytest.raises(ValueError):
            QBAConfig(n_parties=11, size_l=4, qsim_path="dense")  # 48 qubits


class TestConsistentAfterAppend:
    def test_matches_composition_randomized(self):
        # consistent_after_append(v, ev, p, li) must equal
        # (consistent(v, append_own(ev, p, li)), its count) everywhere.
        from qba_tpu.core import consistent_after_append

        rng = np.random.default_rng(7)
        size_l, max_l, w = 8, 4, 4
        for _ in range(300):
            # inclusive upper bound: full evidence (count == max_l) is the
            # case where append_own silently drops the own row
            n_rows = int(rng.integers(0, max_l + 1))
            ev = empty_evidence(max_l, size_l)
            vals, lens = np.array(ev.vals), np.array(ev.lens)
            for i in range(n_rows):
                p_i = rng.random(size_l) < 0.5
                vals[i] = np.where(p_i, rng.integers(0, w + 2, size_l), -1)
                lens[i] = int(p_i.sum())
            ev = Evidence(
                vals=jnp.asarray(vals),
                lens=jnp.asarray(lens),
                count=jnp.asarray(n_rows, dtype=jnp.int32),
            )
            p = jnp.asarray(rng.random(size_l) < 0.5)
            li = jnp.asarray(rng.integers(0, w, size_l), dtype=jnp.int32)
            v = jnp.asarray(int(rng.integers(0, w)), dtype=jnp.int32)

            appended = append_own(ev, p, li)
            want = bool(consistent(v, appended, w)), int(appended.count)
            got_ok, got_count = consistent_after_append(v, ev, p, li, w)
            assert (bool(got_ok), int(got_count)) == want
