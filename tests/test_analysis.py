"""Static invariant checker (``qba-tpu lint``): the lint must be
silent on the shipped tree and loud on every seeded Known-Issue
regression in ``tests/analysis_fixtures/``.

The fixture tests are the adversarial half of the contract: a
clean-tree zero-findings assertion alone would also pass for a lint
that checks nothing.
"""

import io
import os

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.analysis.dots import BF16_EXACT_MAX, check_dots
from qba_tpu.analysis.driver import lint_configs, run_lint
from qba_tpu.analysis.intervals import IntervalInterpreter, IVal
from qba_tpu.analysis.memory import (
    NORTH_STAR_CEILING_BAND,
    check_memory,
    trial_ceiling,
)
from qba_tpu.analysis.vma import check_spmd_call_sites, check_vma
from qba_tpu.config import QBAConfig

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")

#: The matrix's cheap point: every engine live (fused plan resolves),
#: even lieutenant count so the 2-way sharded variants trace.
CHEAP = QBAConfig(17, 16, 4)


# ---------------------------------------------------------------------------
# Clean tree: the shipped kernels uphold KI-1/KI-2/KI-3 by construction.


def test_clean_tree_zero_findings():
    report = run_lint(configs=[("cheap", CHEAP)])
    assert report.ok, report.render(verbose=True)
    # All 13 build paths of the cheap config must actually have traced —
    # a lint that silently skips paths would also report zero findings.
    # (12 through round 7; the trial megakernel adds pallas_mega/trial.)
    assert report.stats["paths_traced"] == 13
    assert report.stats["dots_checked"] > 0
    assert not report.stats["unhandled_primitives"]
    assert report.stats["vma_builds_checked"] == 3
    assert report.stats["memory_probes_fired"] == 0


def test_lint_matrix_covers_planner_phases():
    labels = [label for label, _ in lint_configs()]
    assert labels == [
        "cheap", "north-star", "f32-gdt", "stabilizer", "split-strategy"
    ]
    # The stabilizer point pins the batched GF(2) resource path.
    assert any(
        c.qsim_path == "stabilizer" for _, c in lint_configs()
    )
    # The split point pins the FORGE_P effect path through the gates.
    assert any(c.strategy == "split" for _, c in lint_configs())
    # The north-star point is the calibration anchor; losing it from
    # the matrix silently drops the HBM-band check.
    assert (33, 64, 10) in [
        (c.n_parties, c.size_l, c.n_dishonest) for _, c in lint_configs()
    ]


def test_gf2_engine_lint_clean():
    # Acceptance criterion (ISSUE 7): every GF(2) parity dot on the
    # batched stabilizer path proves KI-3-clean from the interval seeds
    # alone — no Precision.HIGHEST, zero allowlist markers.
    stab = QBAConfig(11, 16, 3, qsim_path="stabilizer")
    report = run_lint(configs=[("stabilizer", stab)], engines=["gf2"])
    assert report.ok, report.render(verbose=True)
    assert report.stats["paths_traced"] == 3
    assert report.stats["dots_checked"] > 0
    assert report.stats["dots_skipped_nonintegral"] == 0
    assert not report.stats["unhandled_primitives"]
    assert not any("allowlisted" in n for n in report.notes)
    # The packed-tableau KI-2 entry must have fired as a note.
    assert any("gf2-tableau" in n for n in report.notes)
    # And the source itself carries no exact-ok escape hatches (the
    # marker is only live in a comment; linalg.py's docstring names it
    # in prose to state this very contract).
    gf2_dir = os.path.join(
        os.path.dirname(__file__), os.pardir, "qba_tpu", "gf2"
    )
    for fname in os.listdir(gf2_dir):
        if fname.endswith(".py"):
            with open(os.path.join(gf2_dir, fname)) as fh:
                assert "# qba-lint: exact-ok" not in fh.read(), fname


def test_cli_lint_clean(capsys):
    from qba_tpu.cli import main

    out = io.StringIO()
    rc = main(["lint", "--config", "5,4,1", "--engines", "xla"], out=out)
    assert rc == 0
    assert "0 finding(s)" in out.getvalue()


# ---------------------------------------------------------------------------
# KI-3: the exact-dot pass and its interval domain.


def _dot_records(fn, args, seeds):
    closed = jax.make_jaxpr(fn)(*args)
    interp = IntervalInterpreter("fixture")
    interp.run(closed, seeds)
    return list(interp.dots.values())


def test_ki3_bad_meta_gather_flagged():
    from tests.analysis_fixtures.bad_meta_gather import bad_meta_gather

    records = _dot_records(
        bad_meta_gather,
        (jnp.zeros((64, 512), jnp.float32), jnp.zeros((512, 4), jnp.int32)),
        [IVal(0, 1, True), IVal(0, 511, True)],
    )
    report = check_dots(records)
    assert not report.ok
    assert [f.ki for f in report.findings] == ["KI-3"]
    f = report.findings[0]
    assert f.check == "exact-dot"
    assert "511" in f.message and str(BF16_EXACT_MAX) in f.message


def test_ki3_shipped_gather_form_passes():
    from tests.analysis_fixtures.bad_meta_gather import good_meta_gather

    records = _dot_records(
        good_meta_gather,
        (jnp.zeros((64, 512), jnp.float32), jnp.zeros((512, 4), jnp.int32)),
        [IVal(0, 1, True), IVal(0, 511, True)],
    )
    report = check_dots(records)
    assert report.ok, report.render()
    assert report.stats["dots_explicit_precision"] == 1


def test_ki3_onehot_structure_bounds_gather_result():
    # The structural half of the domain: a one-hot contraction selects
    # one row, so the result inherits the table's bound instead of the
    # sum-over-K blowup — this is what lets the shipped accumulator
    # dots downstream of a gather stay below 256 without annotations.
    def gather_then_sum(col, table):
        oh = (
            jax.lax.broadcasted_iota(jnp.int32, (8, 512), 1) == col
        ).astype(jnp.float32)
        g = jnp.dot(oh, table.astype(jnp.float32),
                    precision=jax.lax.Precision.HIGHEST)
        return jnp.dot(jnp.ones((4, 8), jnp.float32), g)

    closed = jax.make_jaxpr(gather_then_sum)(
        jnp.zeros((8, 1), jnp.int32), jnp.zeros((512, 4), jnp.int32)
    )
    interp = IntervalInterpreter("unit")
    interp.run(closed, [IVal(0, 511, True), IVal(0, 300, True)])
    report = check_dots(interp.dots.values())
    # The second dot is default precision with the gathered rows as its
    # rhs: it must be flagged (300 > 256), and the recorded bound must
    # be the table's 300 — one row selected — not 300 * K = 153600.
    assert [f.ki for f in report.findings] == ["KI-3"]
    gather_rec = next(
        r for r in interp.dots.values()
        if "HIGHEST" in str(r.eqn.params.get("precision"))
    )
    assert gather_rec.lhs.mag <= 1
    out_rec = next(
        r for r in interp.dots.values() if r is not gather_rec
    )
    assert out_rec.rhs.bounded and out_rec.rhs.mag == 300


# ---------------------------------------------------------------------------
# KI-1: vma threading, call sites, policy.


def test_ki1_clean_tree():
    report = check_vma(CHEAP)
    assert report.ok, report.render(verbose=True)
    assert report.stats["vma_call_sites_checked"] >= 4


def test_ki1_bad_call_sites_flagged():
    report = check_spmd_call_sites(
        os.path.join(FIXTURES, "bad_vma_spmd.py")
    )
    assert {f.ki for f in report.findings} == {"KI-1"}
    messages = sorted(f.message for f in report.findings)
    assert len(messages) == 2
    assert any("without an out_vma" in m for m in messages)
    assert any("out_vma=None" in m for m in messages)
    # Both findings carry a clickable fixture location.
    assert all("bad_vma_spmd.py:" in f.where for f in report.findings)


def test_ki1_policy_env_roundtrip(monkeypatch):
    from qba_tpu.parallel.spmd import _tiled_check_vma

    monkeypatch.setenv("QBA_TILED_CHECK_VMA", "1")
    assert _tiled_check_vma() is True
    monkeypatch.setenv("QBA_TILED_CHECK_VMA", "0")
    assert _tiled_check_vma() is False
    monkeypatch.setenv("QBA_TILED_CHECK_VMA", "junk")
    with pytest.raises(ValueError):
        _tiled_check_vma()


# ---------------------------------------------------------------------------
# KI-2: static plan audit.


def test_ki2_bad_block_plan_flagged():
    from tests.analysis_fixtures.bad_block_plan import bad_config

    report = check_memory(bad_config())
    assert not report.ok
    assert {f.ki for f in report.findings} == {"KI-2"}
    assert any(
        "explicit tiled_block=256" in f.message for f in report.findings
    )


def test_ki2_clean_tree():
    report = check_memory(CHEAP)
    assert report.ok, report.render(verbose=True)
    assert report.stats["memory_probes_fired"] == 0


def test_ki2_north_star_ceiling_in_measured_band():
    lo, hi = NORTH_STAR_CEILING_BAND
    assert lo <= trial_ceiling(QBAConfig(33, 64, 10)) <= hi
