"""Racy-delivery model (docs/DIVERGENCES.md D1).

The reference's barrier race silently loses packets that miss their
round's ``Iprobe`` drain (``tfg.py:294,341``); ``delivery="racy"`` models
it as an independent per-(packet, receiver) loss with prob ``p_late``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.config import QBAConfig
from qba_tpu.rounds import run_trial


def batch(cfg, seed, n):
    keys = jax.random.split(jax.random.key(seed), n)
    return jax.jit(jax.vmap(lambda k: run_trial(cfg, k)))(keys)


class TestRacyDelivery:
    def test_p_late_zero_is_bit_identical_to_sync(self):
        sync = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=8)
        racy = dataclasses.replace(sync, delivery="racy", p_late=0.0)
        a, b = batch(sync, 3, 8), batch(racy, 3, 8)
        assert a.decisions.tolist() == b.decisions.tolist()
        assert a.vi.tolist() == b.vi.tolist()

    def test_total_loss_keeps_validity_with_honest_commander(self):
        # p_late=1: all round traffic is lost; honest lieutenants keep only
        # their step-3a accept (direct commander receive, tfg.py:185-196),
        # so with an honest commander every honest party still decides v.
        cfg = QBAConfig(
            n_parties=5, size_l=32, n_dishonest=2,
            delivery="racy", p_late=1.0,
        )
        r = batch(cfg, 4, 32)
        comm_honest = r.honest[:, 0]
        ok = r.decisions[:, 1:] == r.v_comm[:, None]
        lieu_honest = r.honest[:, 1:]
        assert bool(jnp.all(~comm_honest[:, None] | ~lieu_honest | ok))

    def test_loss_degrades_equivocation_detection(self):
        # Under a dishonest commander the protocol needs relay traffic to
        # converge; heavy loss must not crash and still yields a verdict.
        cfg = QBAConfig(
            n_parties=5, size_l=32, n_dishonest=1,
            delivery="racy", p_late=0.9,
        )
        r = batch(cfg, 5, 32)
        assert r.success.shape == (32,)

    @pytest.mark.parametrize("p_late", [0.0, 0.5, 1.0])
    def test_local_and_native_backends_match_jax(self, p_late):
        from qba_tpu.backends.native_backend import run_trial_native
        from qba_tpu.native import available

        cfg = QBAConfig(
            n_parties=4, size_l=8, n_dishonest=1,
            delivery="racy", p_late=p_late,
        )
        has_native = available()
        keys = jax.random.split(jax.random.key(6), 6)
        for k in keys:
            a = run_trial(cfg, k)
            b = run_trial_local(cfg, k)
            assert [int(x) for x in a.decisions] == b["decisions"]
            assert bool(a.success) == b["success"]
            if has_native:
                c = run_trial_native(cfg, k)
                assert c["decisions"] == b["decisions"]
                assert c["vi"] == b["vi"]


class TestConfigValidation:
    def test_p_late_requires_racy(self):
        with pytest.raises(ValueError):
            QBAConfig(n_parties=3, size_l=4, p_late=0.5)

    def test_unknown_delivery_rejected(self):
        with pytest.raises(ValueError):
            QBAConfig(n_parties=3, size_l=4, delivery="laplacian")


class TestDeferMode:
    """racy_mode="defer": the reference's actual race mechanism — a late
    packet arrives one round later and the evidence-length check rejects
    it (tfg.py:294) — must be decision-equivalent to the modeled loss
    (docs/DIVERGENCES.md D1)."""

    def _cfg(self, **kw):
        return QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2,
            delivery="racy", p_late=0.5, **kw,
        )

    def test_defer_equals_loss_decisions(self):
        from qba_tpu.backends.local_backend import run_trial_local
        from qba_tpu.rounds import run_trial

        cfg_defer = self._cfg(racy_mode="defer")
        cfg_loss = self._cfg()
        keys = jax.random.split(jax.random.key(11), 12)
        for k in keys:
            d = run_trial_local(cfg_defer, k)
            l = run_trial_local(cfg_loss, k)
            assert d["decisions"] == l["decisions"]
            assert d["vi"] == l["vi"]
            assert d["overflow"] == l["overflow"]
            # ... and both match the vectorized engine's loss semantics.
            a = run_trial(cfg_loss, k)
            assert [int(x) for x in a.decisions] == d["decisions"]

    def test_native_engine_runs_defer_mechanism(self):
        # VERDICT r2 item 5: the C++ engine executes the defer mechanism
        # (deferred queues, next-round re-drain) rather than remapping to
        # loss — decisions match the local defer run and the trail shows
        # the deferred deliveries.
        from qba_tpu.backends.local_backend import run_trial_local
        from qba_tpu.backends.native_backend import run_trial_native
        from qba_tpu.obs import EventLog, Level

        cfg = self._cfg(racy_mode="defer")
        saw_deferred = False
        for seed in range(6):
            k = jax.random.key(seed)
            log = EventLog(Level.DEBUG)
            rn = run_trial_native(cfg, k, log=log)
            rl = run_trial_local(cfg, k)
            assert rn["decisions"] == rl["decisions"]
            assert rn["vi"] == rl["vi"]
            for e in log.events:
                if e.fields.get("deferred"):
                    saw_deferred = True
                    assert not e.fields["accepted"]  # D1 invariant
        assert saw_deferred

    def test_deferred_packets_never_accepted(self):
        # Deferred re-deliveries carry deferred=True in the trail; the
        # D1 invariant is that NONE is ever accepted, and the mechanism
        # shows as wrong-evidence-len for the ones that get that far.
        from qba_tpu.backends.local_backend import run_trial_local
        from qba_tpu.obs import EventLog, Level

        cfg = self._cfg(racy_mode="defer")
        n_deferred = n_evlen = 0
        for seed in range(8):
            log = EventLog(Level.DEBUG)
            run_trial_local(cfg, jax.random.key(seed), log=log)
            for e in log.events:
                if e.message == "receive" and e.fields.get("deferred"):
                    assert not e.fields["accepted"], e.fields
                    n_deferred += 1
                    n_evlen += e.fields["reason"] == "wrong-evidence-len"
                if e.message == "late defer":
                    pass  # the deferral itself is logged too
        assert n_deferred > 0, "p_late=0.5 produced no deferred delivery"
        assert n_evlen > 0, "no deferred packet reached the evidence-len check"

    def test_defer_requires_racy_delivery(self):
        with pytest.raises(ValueError, match="racy_mode"):
            QBAConfig(n_parties=3, size_l=4, racy_mode="defer")
        with pytest.raises(ValueError, match="racy_mode"):
            QBAConfig(n_parties=3, size_l=4, racy_mode="sometimes")
