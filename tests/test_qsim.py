"""qsim engine tests: dense statevector correctness, closed-form properties
of both generation paths (SURVEY §2.6), and dense-vs-factorized
cross-validation.

Properties are checked against what the captured reference logs verify
(SURVEY §2.6): pairwise-distinct party values at Q-correlated positions
(``log_11.txt:13-24``) and ``L1 == Lc`` at non-correlated positions.
"""

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.qsim import generate_lists, generate_lists_dense
from qba_tpu.qsim import statevector as sv
from qba_tpu.qsim.circuit import Circuit, Gate


class TestStatevector:
    def test_x_flips(self):
        state = sv.init_state(2)
        state = sv.apply_1q(state, sv.X, 0)
        bits = sv.measure_all(state, jax.random.key(0))
        assert bits.tolist() == [1, 0]

    def test_h_uniform(self):
        state = sv.apply_1q(sv.init_state(1), sv.H, 0)
        keys = jax.random.split(jax.random.key(1), 2000)
        bits = jax.vmap(lambda k: sv.measure_all(state, k))(keys)
        frac = float(jnp.mean(bits[:, 0]))
        assert 0.45 < frac < 0.55

    def test_cnot_copies(self):
        # |+>|0> -> Bell pair: measurements always agree
        state = sv.apply_1q(sv.init_state(2), sv.H, 0)
        state = sv.apply_controlled_1q(state, sv.X, 1, (0,))
        keys = jax.random.split(jax.random.key(2), 500)
        bits = jax.vmap(lambda k: sv.measure_all(state, k))(keys)
        assert bool(jnp.all(bits[:, 0] == bits[:, 1]))
        assert 0.4 < float(jnp.mean(bits[:, 0])) < 0.6

    def test_controlled_requires_all_controls(self):
        # |10> with controls (1,): control qubit is 0 -> no flip of target 0
        state = sv.apply_1q(sv.init_state(2), sv.X, 0)
        state = sv.apply_controlled_1q(state, sv.X, 0, (1,))
        assert sv.measure_all(state, jax.random.key(0)).tolist() == [1, 0]

    def test_xpow(self):
        state = sv.init_state(1)
        s0 = sv.apply_1q(state, sv.xpow_matrix(jnp.asarray(0)), 0)
        s1 = sv.apply_1q(state, sv.xpow_matrix(jnp.asarray(1)), 0)
        assert sv.measure_all(s0, jax.random.key(0)).tolist() == [0]
        assert sv.measure_all(s1, jax.random.key(0)).tolist() == [1]


class TestCircuitBuilder:
    def test_validation(self):
        g = Gate(2)
        for bad in (lambda: g.add_operation("FOO", targets=0),
                    lambda: g.add_operation("X", targets=5),
                    lambda: g.add_operation("X", targets=0, controls=0),
                    lambda: g.add_operation("XPOW", targets=0),
                    lambda: g.add_operation("RY", targets=0),  # no angle
                    lambda: g.add_operation("Z", targets=0, angle=0.5)):
            try:
                bad()
                raise AssertionError("expected ValueError")
            except ValueError:
                pass
        c = Circuit(3)
        try:
            c.add_operation(Gate(2))
            raise AssertionError("expected ValueError")
        except ValueError:
            pass

    def test_compiled_circuit_is_vmappable(self):
        g = Gate(2).add_operation("H", targets=0).add_operation(
            "X", targets=1, controls=0
        )
        run = Circuit(2).add_operation(g).compile()
        keys = jax.random.split(jax.random.key(3), 100)
        bits = jax.jit(jax.vmap(run))(keys)
        assert bool(jnp.all(bits[:, 0] == bits[:, 1]))


def check_closed_form_properties(lists, qcorr, w):
    """The §2.6 invariants every generation path must satisfy."""
    lists, qcorr = np.asarray(lists), np.asarray(qcorr)
    n_rows = lists.shape[0]
    assert lists.min() >= 0 and lists.max() < w
    # Non-correlated positions: QSD copy equals commander's list.
    nq = ~qcorr
    np.testing.assert_array_equal(lists[0, nq], lists[1, nq])
    # Q-correlated positions: all rows pairwise distinct, and
    # {row_i XOR row_0 : i >= 1} is exactly {1..nParties}.
    q = qcorr
    xors = lists[1:, q] ^ lists[0:1, q]
    for k in range(q.sum()):
        got = sorted(xors[:, k].tolist())
        assert got == list(range(1, n_rows)), got


class TestFactorizedSampler:
    def test_closed_form_properties(self):
        cfg = QBAConfig(n_parties=11, size_l=256)
        lists, qcorr = generate_lists(cfg, jax.random.key(0))
        assert lists.shape == (12, 256)
        check_closed_form_properties(lists, qcorr, cfg.w)

    def test_commander_recovers_qcorr_exactly(self):
        # isQCorr = {k : L1[k] != Lc[k]} (tfg.py:327) must equal the mask
        cfg = QBAConfig(n_parties=5, size_l=512)
        lists, qcorr = generate_lists(cfg, jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(lists[0] != lists[1]),
                                      np.asarray(qcorr))

    def test_value_distributions_chi_square(self):
        # Full w-value laws at significance 1e-4 (VERDICT r1 #7):
        # the shared random value r (row 0 at Q-corr positions) is uniform
        # over [0, w); every party row's marginal is uniform over [0, w)
        # (r XOR rands[i] at Q-corr, i.i.d. uniform elsewhere — SURVEY
        # §2.6); and each party's XOR offset at Q-corr positions is
        # uniform over {1..nParties} (a uniformly random permutation
        # coordinate).
        from scipy import stats

        cfg = QBAConfig(n_parties=3, size_l=4096)
        lists, qcorr = generate_lists(cfg, jax.random.key(2))
        lists, qcorr = np.asarray(lists), np.asarray(qcorr)
        r = lists[0][qcorr]
        assert stats.chisquare(np.bincount(r, minlength=cfg.w)).pvalue > 1e-4
        for row in lists:
            obs = np.bincount(row, minlength=cfg.w)
            assert stats.chisquare(obs).pvalue > 1e-4
        xors = lists[1:, qcorr] ^ lists[0:1, qcorr]
        for i in range(cfg.n_parties):
            obs = np.bincount(xors[i], minlength=cfg.n_parties + 1)[1:]
            assert stats.chisquare(obs).pvalue > 1e-4


class TestDensePath:
    def test_closed_form_properties(self):
        cfg = QBAConfig(n_parties=3, size_l=64, qsim_path="dense")
        lists, qcorr = generate_lists_dense(cfg, jax.random.key(3))
        assert lists.shape == (4, 64)
        check_closed_form_properties(lists, qcorr, cfg.w)

    def test_cross_validates_factorized(self):
        # Same marginal stats from both engines at nParties=3.
        cfg = QBAConfig(n_parties=3, size_l=1024)
        ld, qd = generate_lists_dense(cfg, jax.random.key(4))
        lf, qf = generate_lists(cfg, jax.random.key(5))
        for lists, qcorr in ((ld, qd), (lf, qf)):
            check_closed_form_properties(lists, qcorr, cfg.w)
        from scipy import stats

        # qcorr is Bernoulli(1/2) on both paths (binomial exact test at
        # significance 1e-4).
        for q in (qd, qf):
            k = int(np.asarray(q).sum())
            p = stats.binomtest(k, cfg.size_l, 0.5).pvalue
            assert p > 1e-4, (k, cfg.size_l)
        # Full w-value distribution uniform for every party row on both
        # paths (chi-square at significance 1e-4) — the cross-validation
        # VERDICT r1 #7 asked to harden.
        for lists in (ld, lf):
            for row in np.asarray(lists):
                obs = np.bincount(row, minlength=cfg.w)
                assert stats.chisquare(obs).pvalue > 1e-4


class TestExtendedGates:
    """The broadened gate surface (VERDICT r1 #6): Z/Y/S/T, CZ/CNOT via
    controls, RX/RY/RZ/P rotations, multi-shot batching — so the qsimov
    replacement survives reference-style circuits beyond the two protocol
    families (tfg.py:4, SURVEY 2.16)."""

    def test_gate_matrices_unitary(self):
        import itertools

        kinds = [("H", None), ("X", None), ("Y", None), ("Z", None),
                 ("S", None), ("T", None)]
        kinds += [(k, a) for k, a in itertools.product(
            ("RX", "RY", "RZ", "P"), (0.0, 0.37, np.pi / 2, np.pi))]
        for kind, angle in kinds:
            m = sv.gate_matrix(kind, angle)
            np.testing.assert_allclose(
                m @ m.conj().T, np.eye(2), atol=1e-6,
                err_msg=f"{kind}({angle}) not unitary",
            )

    def test_known_matrix_identities(self):
        np.testing.assert_allclose(
            sv.gate_matrix("S"), sv.gate_matrix("T") @ sv.gate_matrix("T"),
            atol=1e-6,
        )
        np.testing.assert_allclose(
            sv.gate_matrix("Z"), sv.gate_matrix("S") @ sv.gate_matrix("S"),
            atol=1e-6,
        )
        # RY(pi) = -iY; P(pi) = Z; HZH = X
        np.testing.assert_allclose(
            sv.gate_matrix("RY", np.pi), -1j * sv.gate_matrix("Y"), atol=1e-6
        )
        np.testing.assert_allclose(
            sv.gate_matrix("P", np.pi), sv.gate_matrix("Z"), atol=1e-6
        )
        h = sv.gate_matrix("H")
        np.testing.assert_allclose(
            h @ sv.gate_matrix("Z") @ h, sv.gate_matrix("X"), atol=1e-6
        )

    def _demo_circuit(self, n):
        """A non-protocol circuit using every new gate family, with
        targets/controls on both sides of the Pallas row/lane split."""
        c = Circuit(n)
        g = Gate(n)
        g.add_operation("H", targets=0)
        g.add_operation("H", targets=n - 1)
        g.add_operation("S", targets=0)
        g.add_operation("T", targets=n - 1)
        g.add_operation("Y", targets=min(2, n - 1))
        g.add_operation("RZ", targets=min(3, n - 1), angle=0.7)
        g.add_operation("Z", targets=n - 1, controls=0)  # CZ
        g.add_operation("X", targets=min(1, n - 1), controls=n - 1)  # CNOT
        g.add_operation("RX", targets=0, angle=1.1)
        g.add_operation("RY", targets=n - 2, angle=0.4, controls=min(2, n - 1))
        g.add_operation("P", targets=min(4, n - 1), angle=2.2)
        c.add_operation(g)
        return c

    def test_xla_vs_fused_pallas_complex(self):
        # n=9 puts two qubits in the Pallas row dimension, the rest in
        # lanes — both butterfly and MXU paths execute complex gates.
        for n in (5, 9):
            c = self._demo_circuit(n)
            s_xla = np.asarray(c.compile_state("xla")())
            s_pl = np.asarray(c.compile_state("pallas_interpret")())
            assert s_pl.dtype == np.complex64
            np.testing.assert_allclose(s_pl, s_xla, atol=1e-5)
            np.testing.assert_allclose(np.linalg.norm(s_pl), 1.0, atol=1e-5)

    def test_real_circuits_keep_float32_fast_path(self):
        c = Circuit(8)
        g = Gate(8)
        g.add_operation("H", targets=0)
        g.add_operation("Z", targets=3)
        g.add_operation("RY", targets=7, angle=0.3)
        g.add_operation("X", targets=2, controls=0)
        c.add_operation(g)
        s_pl = np.asarray(c.compile_state("pallas_interpret")())
        assert s_pl.dtype == np.float32  # no imag state materialized
        np.testing.assert_allclose(
            s_pl, np.asarray(c.compile_state("xla")()).real, atol=1e-6
        )

    def test_measure_shots_matches_born_distribution(self):
        # chi-square at significance 1e-4 over the full 2**n outcome set.
        from scipy import stats

        c = self._demo_circuit(5)
        state = c.compile_state("xla")()
        probs = np.abs(np.asarray(state)) ** 2
        bits = np.asarray(
            c.compile_shots("xla")(jax.random.key(3), 4000)
        )
        assert bits.shape == (4000, 5)
        idx = (bits * (2 ** np.arange(4, -1, -1))).sum(axis=1)
        obs = np.bincount(idx, minlength=32).astype(float)
        exp = 4000 * probs / probs.sum()
        # Pool outcomes with expected count < 5 (chi-square validity rule).
        big = exp >= 5
        obs_b = np.append(obs[big], obs[~big].sum())
        exp_b = np.append(exp[big], exp[~big].sum())
        p = stats.chisquare(obs_b, exp_b * obs_b.sum() / exp_b.sum())
        assert p.pvalue > 1e-4

    def test_compat_ghz_demo(self):
        # The reference-style API executes a non-protocol GHZ circuit:
        # only |000> and |111> outcomes, ~50/50 (tfg.py:4 claims a general
        # engine; this pins the compat shim beyond the protocol families).
        from qba_tpu.qsim.compat import Drewom, QCircuit

        circ = QCircuit(3, 3, "ghz")
        circ.add_operation("H", targets=0)
        circ.add_operation("X", targets=1, controls=0)
        circ.add_operation("X", targets=2, controls=1)
        for q in range(3):
            circ.add_operation("MEASURE", targets=q, outputs=q)
        shots = Drewom(seed=7).execute(circ, shots=400)
        assert len(shots) == 400
        outcomes = {tuple(s) for s in shots}
        assert outcomes <= {(0, 0, 0), (1, 1, 1)}
        frac = sum(1 for s in shots if s == [1, 1, 1]) / 400
        assert 0.4 < frac < 0.6

    def test_compat_rotation_demo(self):
        # RY(2*pi/3) on |0> gives P(1) = sin^2(pi/3) = 3/4.
        from qba_tpu.qsim.compat import Drewom, QCircuit

        circ = QCircuit(1, 1, "ry")
        circ.add_operation("RY", targets=0, angle=2 * np.pi / 3)
        circ.add_operation("MEASURE", targets=0, outputs=0)
        shots = Drewom(seed=1).execute(circ, shots=2000)
        frac = sum(s[0] for s in shots) / 2000
        assert abs(frac - 0.75) < 0.04


class TestStabilizer:
    """The Clifford-tableau executor (VERDICT r4 item 1): runs the
    reference's ACTUAL joint-circuit construction at its real scale —
    48 qubits at 11 parties (proven feasible by ``log_11.txt``), 204 at
    33 — through the same circuit API, closing SURVEY §2.16.

    Validation strategy: (a) differential against the dense engine on
    random small Clifford circuits — the sampled support must be the
    dense support exactly (signs wrong => wrong support) and the
    frequencies chi-square-consistent; (b) the protocol circuits'
    per-shot structural laws, which are EXACT (group_i = r XOR
    rands[i-1] at Q-corr, group0 == group1 at not-Q-corr); (c) the
    full §2.6 closed-form invariants + value-law chi-squares at the
    11-party scale the factorized sampler was previously validated at
    only indirectly."""

    def _random_clifford(self, n, depth, rng, with_xpow=False):
        c = Circuit(n)
        g = Gate(n)
        p = 0
        for _ in range(depth):
            kind = rng.choice(
                ["H", "X", "Y", "Z", "CNOT", "CZ"]
                + (["XPOW"] if with_xpow else [])
            )
            if kind in ("CNOT", "CZ"):
                a, b = rng.sample(range(n), 2)
                g.add_operation(
                    "X" if kind == "CNOT" else "Z", targets=a, controls=b
                )
            elif kind == "XPOW":
                g.add_operation("XPOW", targets=rng.randrange(n), param=p)
                p += 1
            else:
                g.add_operation(kind, targets=rng.randrange(n))
        c.add_operation(g)
        return c

    def test_differential_vs_dense_random_clifford(self):
        # Support must match exactly (a single sign error puts samples
        # outside the dense support) and frequencies must be
        # chi-square-consistent at significance 1e-4.
        import random as pyrandom

        from scipy import stats

        rng = pyrandom.Random(0)
        shots = 4000
        for trial in range(6):
            n = rng.choice([3, 4, 5])
            c = self._random_clifford(n, 14, rng, with_xpow=trial >= 3)
            n_par = max(c.n_params, 1)
            params = jnp.asarray(
                [rng.randrange(2) for _ in range(n_par)], dtype=jnp.int32
            )
            probs = np.abs(
                np.asarray(c.compile_state("xla")(params))
            ) ** 2
            run = jax.jit(c.compile_shots("stabilizer"), static_argnums=1)
            bits = np.asarray(run(jax.random.key(trial), shots, params))
            idx = (bits * (2 ** np.arange(n - 1, -1, -1))).sum(-1)
            emp = np.bincount(idx, minlength=2**n)
            sup = probs > 1e-9
            assert emp[~sup].sum() == 0, (
                f"trial {trial}: sampled outside the dense support"
            )
            if sup.sum() > 1:  # dof 0 on deterministic circuits
                pv = stats.chisquare(
                    emp[sup], shots * probs[sup] / probs[sup].sum()
                ).pvalue
                assert pv > 1e-4, (trial, pv)

    def test_rejects_non_clifford(self):
        import pytest

        c = Circuit(2)
        c.add_operation(Gate(2).add_operation("T", targets=0))
        with pytest.raises(ValueError, match="Clifford"):
            c.compile("stabilizer")
        c2 = Circuit(2)
        c2.add_operation(Gate(2).add_operation("S", targets=0))
        with pytest.raises(ValueError, match="Clifford"):
            c2.compile("stabilizer")
        c3 = Circuit(3)
        c3.add_operation(
            Gate(3).add_operation("X", targets=0, controls=(1, 2))
        )
        with pytest.raises(ValueError, match="Clifford"):
            c3.compile("stabilizer")
        c4 = Circuit(2)
        c4.add_operation(Gate(2).add_operation("H", targets=0, controls=1))
        with pytest.raises(ValueError, match="stabilizer engine"):
            c4.compile("stabilizer")

    def test_no_statevector(self):
        import pytest

        c = Circuit(2)
        c.add_operation(Gate(2).add_operation("H", targets=0))
        with pytest.raises(ValueError, match="no statevector"):
            c.compile_state("stabilizer")

    def test_reference_scale_48_qubits_exact_law(self):
        # The reference's real 11-party construction (tfg.py:43-52,
        # proven feasible by log_11.txt): one Born sample of the
        # 48-qubit joint Q-correlated circuit must satisfy the EXACT
        # per-shot law group_i = r XOR rands[i-1].
        from qba_tpu.qsim.protocol_circuits import (
            _perm_bits,
            gen_q_corr_circuit,
        )

        n_p, nq = 11, 4
        run = jax.jit(gen_q_corr_circuit(n_p, nq).compile("stabilizer"))
        perm = jax.random.permutation(
            jax.random.key(3), jnp.arange(1, n_p + 1, dtype=jnp.int32)
        )
        for seed in range(3):
            bits = np.asarray(run(jax.random.key(seed), _perm_bits(perm, nq)))
            vals = (
                bits.reshape(n_p + 1, nq) * (2 ** np.arange(nq - 1, -1, -1))
            ).sum(-1)
            expect = np.concatenate([[vals[0]], vals[0] ^ np.asarray(perm)])
            np.testing.assert_array_equal(vals, expect)

    def test_reference_scale_204_qubits_smoke(self):
        # 33 parties = 34 groups x 6 qubits = 204 qubits: far beyond
        # any statevector, exact on the tableau.
        from qba_tpu.qsim.protocol_circuits import (
            _perm_bits,
            gen_q_corr_circuit,
        )

        n_p, nq = 33, 6
        run = jax.jit(gen_q_corr_circuit(n_p, nq).compile("stabilizer"))
        perm = jax.random.permutation(
            jax.random.key(4), jnp.arange(1, n_p + 1, dtype=jnp.int32)
        )
        bits = np.asarray(run(jax.random.key(0), _perm_bits(perm, nq)))
        vals = (
            bits.reshape(n_p + 1, nq) * (2 ** np.arange(nq - 1, -1, -1))
        ).sum(-1)
        expect = np.concatenate([[vals[0]], vals[0] ^ np.asarray(perm)])
        np.testing.assert_array_equal(vals, expect)

    def test_drewom_executes_11_party_joint_circuit(self):
        # VERDICT r4 done-criterion: Drewom().execute() of the 11-party
        # joint circuit runs — the reference's three-line usage
        # (tfg.py:76-80) at its real scale, via the qsimov-shaped API.
        from qba_tpu.qsim.compat import Drewom, QCircuit, QGate

        n_p, nq = 11, 4
        size = (n_p + 1) * nq
        gate = QGate(size, 0, "not Q-Correlated")
        for i in range(nq, size):
            gate.add_operation("H", targets=i)
        for i in range(nq):
            gate.add_operation("X", targets=i, controls=i + nq)
        circ = QCircuit(size, size, "nqc")
        circ.add_operation(gate)
        for i in range(size):
            circ.add_operation("MEASURE", targets=i, outputs=i)
        res = Drewom(seed=3).execute(circ, shots=8)
        assert len(res) == 8 and len(res[0]) == size
        for shot in res:
            vals = (
                np.array(shot).reshape(n_p + 1, nq)
                * (2 ** np.arange(nq - 1, -1, -1))
            ).sum(-1)
            assert vals[0] == vals[1]  # CNOT copy law, exact per shot

    def test_full_scale_lists_match_factorized_law(self):
        # The §2.6 cross-validation AT THE REFERENCE'S SCALE (VERDICT
        # r4 item 1 done-criterion): lists generated by executing the
        # actual 48-qubit circuits satisfy every exact closed-form
        # invariant, and the value marginals pass chi-square against
        # the factorized sampler's law (uniform on [0, w) per row; r
        # uniform; XOR offsets a uniform permutation coordinate).
        from scipy import stats

        cfg = QBAConfig(n_parties=11, size_l=256, qsim_path="stabilizer")
        lists, qcorr = generate_lists_dense(
            cfg, jax.random.key(6), impl="stabilizer"
        )
        assert lists.shape == (12, 256)
        check_closed_form_properties(lists, qcorr, cfg.w)
        lists, qcorr = np.asarray(lists), np.asarray(qcorr)
        r = lists[0][qcorr]
        assert stats.chisquare(np.bincount(r, minlength=cfg.w)).pvalue > 1e-4
        for row in lists:
            obs = np.bincount(row, minlength=cfg.w)
            assert stats.chisquare(obs).pvalue > 1e-4
        # Direct two-sample check against the factorized sampler on the
        # commander row (same law <=> same protocol-visible inputs).
        lf, _ = generate_lists(cfg, jax.random.key(7))
        a = np.bincount(lists[1], minlength=cfg.w)
        b = np.bincount(np.asarray(lf)[1], minlength=cfg.w)
        table = np.stack([a, b])
        assert stats.chi2_contingency(table).pvalue > 1e-4

    def test_end_to_end_trial_through_stabilizer_lists(self):
        # qsim_path="stabilizer" plugs into the full protocol: lists
        # come from executing the real joint circuits, then the round
        # engines run unchanged (honest config decides successfully).
        from qba_tpu.backends import run_trials

        cfg = QBAConfig(
            n_parties=5, size_l=16, trials=2, qsim_path="stabilizer",
            seed=2,
        )
        out = run_trials(cfg)
        assert np.asarray(out.trials.success).all()
