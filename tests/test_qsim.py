"""qsim engine tests: dense statevector correctness, closed-form properties
of both generation paths (SURVEY §2.6), and dense-vs-factorized
cross-validation.

Properties are checked against what the captured reference logs verify
(SURVEY §2.6): pairwise-distinct party values at Q-correlated positions
(``log_11.txt:13-24``) and ``L1 == Lc`` at non-correlated positions.
"""

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.config import QBAConfig
from qba_tpu.qsim import generate_lists, generate_lists_dense
from qba_tpu.qsim import statevector as sv
from qba_tpu.qsim.circuit import Circuit, Gate


class TestStatevector:
    def test_x_flips(self):
        state = sv.init_state(2)
        state = sv.apply_1q(state, sv.X, 0)
        bits = sv.measure_all(state, jax.random.key(0))
        assert bits.tolist() == [1, 0]

    def test_h_uniform(self):
        state = sv.apply_1q(sv.init_state(1), sv.H, 0)
        keys = jax.random.split(jax.random.key(1), 2000)
        bits = jax.vmap(lambda k: sv.measure_all(state, k))(keys)
        frac = float(jnp.mean(bits[:, 0]))
        assert 0.45 < frac < 0.55

    def test_cnot_copies(self):
        # |+>|0> -> Bell pair: measurements always agree
        state = sv.apply_1q(sv.init_state(2), sv.H, 0)
        state = sv.apply_controlled_1q(state, sv.X, 1, (0,))
        keys = jax.random.split(jax.random.key(2), 500)
        bits = jax.vmap(lambda k: sv.measure_all(state, k))(keys)
        assert bool(jnp.all(bits[:, 0] == bits[:, 1]))
        assert 0.4 < float(jnp.mean(bits[:, 0])) < 0.6

    def test_controlled_requires_all_controls(self):
        # |10> with controls (1,): control qubit is 0 -> no flip of target 0
        state = sv.apply_1q(sv.init_state(2), sv.X, 0)
        state = sv.apply_controlled_1q(state, sv.X, 0, (1,))
        assert sv.measure_all(state, jax.random.key(0)).tolist() == [1, 0]

    def test_xpow(self):
        state = sv.init_state(1)
        s0 = sv.apply_1q(state, sv.xpow_matrix(jnp.asarray(0)), 0)
        s1 = sv.apply_1q(state, sv.xpow_matrix(jnp.asarray(1)), 0)
        assert sv.measure_all(s0, jax.random.key(0)).tolist() == [0]
        assert sv.measure_all(s1, jax.random.key(0)).tolist() == [1]


class TestCircuitBuilder:
    def test_validation(self):
        g = Gate(2)
        for bad in (lambda: g.add_operation("Z", targets=0),
                    lambda: g.add_operation("X", targets=5),
                    lambda: g.add_operation("X", targets=0, controls=0),
                    lambda: g.add_operation("XPOW", targets=0)):
            try:
                bad()
                raise AssertionError("expected ValueError")
            except ValueError:
                pass
        c = Circuit(3)
        try:
            c.add_operation(Gate(2))
            raise AssertionError("expected ValueError")
        except ValueError:
            pass

    def test_compiled_circuit_is_vmappable(self):
        g = Gate(2).add_operation("H", targets=0).add_operation(
            "X", targets=1, controls=0
        )
        run = Circuit(2).add_operation(g).compile()
        keys = jax.random.split(jax.random.key(3), 100)
        bits = jax.jit(jax.vmap(run))(keys)
        assert bool(jnp.all(bits[:, 0] == bits[:, 1]))


def check_closed_form_properties(lists, qcorr, w):
    """The §2.6 invariants every generation path must satisfy."""
    lists, qcorr = np.asarray(lists), np.asarray(qcorr)
    n_rows = lists.shape[0]
    assert lists.min() >= 0 and lists.max() < w
    # Non-correlated positions: QSD copy equals commander's list.
    nq = ~qcorr
    np.testing.assert_array_equal(lists[0, nq], lists[1, nq])
    # Q-correlated positions: all rows pairwise distinct, and
    # {row_i XOR row_0 : i >= 1} is exactly {1..nParties}.
    q = qcorr
    xors = lists[1:, q] ^ lists[0:1, q]
    for k in range(q.sum()):
        got = sorted(xors[:, k].tolist())
        assert got == list(range(1, n_rows)), got


class TestFactorizedSampler:
    def test_closed_form_properties(self):
        cfg = QBAConfig(n_parties=11, size_l=256)
        lists, qcorr = generate_lists(cfg, jax.random.key(0))
        assert lists.shape == (12, 256)
        check_closed_form_properties(lists, qcorr, cfg.w)

    def test_commander_recovers_qcorr_exactly(self):
        # isQCorr = {k : L1[k] != Lc[k]} (tfg.py:327) must equal the mask
        cfg = QBAConfig(n_parties=5, size_l=512)
        lists, qcorr = generate_lists(cfg, jax.random.key(1))
        np.testing.assert_array_equal(np.asarray(lists[0] != lists[1]),
                                      np.asarray(qcorr))

    def test_r_uniformity(self):
        cfg = QBAConfig(n_parties=3, size_l=4096)
        lists, qcorr = generate_lists(cfg, jax.random.key(2))
        r = np.asarray(lists[0])[np.asarray(qcorr)]
        counts = np.bincount(r, minlength=cfg.w)
        expected = len(r) / cfg.w
        chi2 = ((counts - expected) ** 2 / expected).sum()
        assert chi2 < 30, chi2  # 3 dof; extremely loose to avoid flakes


class TestDensePath:
    def test_closed_form_properties(self):
        cfg = QBAConfig(n_parties=3, size_l=64, qsim_path="dense")
        lists, qcorr = generate_lists_dense(cfg, jax.random.key(3))
        assert lists.shape == (4, 64)
        check_closed_form_properties(lists, qcorr, cfg.w)

    def test_cross_validates_factorized(self):
        # Same marginal stats from both engines at nParties=3.
        cfg = QBAConfig(n_parties=3, size_l=1024)
        ld, qd = generate_lists_dense(cfg, jax.random.key(4))
        lf, qf = generate_lists(cfg, jax.random.key(5))
        for lists, qcorr in ((ld, qd), (lf, qf)):
            check_closed_form_properties(lists, qcorr, cfg.w)
        # qcorr rate ~ 1/2 on both paths
        assert abs(float(jnp.mean(qd)) - 0.5) < 0.06
        assert abs(float(jnp.mean(qf)) - 0.5) < 0.06
        # commander-value distribution uniform on both paths (chi2, 3 dof)
        for lists in (ld, lf):
            counts = np.bincount(np.asarray(lists[1]), minlength=cfg.w)
            expected = cfg.size_l / cfg.w
            chi2 = ((counts - expected) ** 2 / expected).sum()
            assert chi2 < 30, chi2
