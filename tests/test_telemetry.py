"""Run-telemetry tests: spans, the run manifest, protocol counters.

Three contracts (docs/OBSERVABILITY.md):

* Spans nest via the recorder's context stack, export as valid Chrome
  trace JSON (``ph: "X"`` complete events with proper time containment),
  and carry the ``fenced`` device-time attribution flag.
* The run manifest validates against its schema, round-trips through
  JSON, and names the actual engine + demotion chain for the resolved
  plan — including the paper's (11,64,3) headline and (33,64,10)
  north-star configs.
* ``collect_counters=True`` adds a :class:`ProtocolCounters` auxiliary
  output WITHOUT perturbing the primary outputs — bit-identical
  decisions/success/vi/overflow on every jit engine.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from qba_tpu.backends.jax_backend import run_trials
from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import (
    QBADemotionWarning,
    QBAProbeWarning,
    record_decisions,
    warn_and_record,
)
from qba_tpu.obs.manifest import (
    MANIFEST_SCHEMA,
    collect_manifest,
    demotion_chain,
    load_manifest,
    telemetry_session,
    validate_manifest,
    write_manifest,
)
from qba_tpu.obs.telemetry import SpanRecorder
from qba_tpu.obs.timers import PhaseTimers

JIT_ENGINES = ("xla", "pallas_tiled", "pallas_fused")


class TestSpans:
    def test_nesting_and_parents(self):
        t = {"now": 0.0}
        rec = SpanRecorder(clock=lambda: t["now"])
        with rec.span("outer", cat="command"):
            t["now"] += 1.0
            with rec.span("inner", chunk=3):
                t["now"] += 2.0
            with rec.span("inner"):
                t["now"] += 0.5
        outer, in1, in2 = rec.spans
        assert (outer.parent, outer.depth) == (None, 0)
        assert in1.parent == outer.index and in1.depth == 1
        assert in2.parent == outer.index
        assert outer.dur == 3.5 and in1.dur == 2.0 and in2.dur == 0.5
        assert in1.args == {"chunk": 3}
        assert rec.totals()["inner"] == {"total_s": 2.5, "count": 2}

    def test_exception_still_closes_span(self):
        rec = SpanRecorder()
        with pytest.raises(RuntimeError):
            with rec.span("doomed"):
                raise RuntimeError("boom")
        assert rec.spans[0].dur is not None
        assert rec._stack == []

    def test_fence_marks_innermost_open_span(self):
        cfg = QBAConfig(n_parties=3, size_l=4, trials=2)
        rec = SpanRecorder()
        with rec.span("trials"):
            res = rec.fence(run_trials(cfg))
        assert rec.spans[0].fenced
        # fence returned the result unchanged (host-readable).
        assert int(np.asarray(res.trials.decisions).shape[0]) == 2

    def test_jsonl_export(self, tmp_path):
        rec = SpanRecorder()
        with rec.span("a", note="x"):
            pass
        path = rec.write_jsonl(str(tmp_path / "spans.jsonl"))
        recs = [json.loads(line) for line in open(path)]
        assert recs[0]["name"] == "a" and recs[0]["args"] == {"note": "x"}
        assert recs[0]["dur_s"] is not None


class TestChromeTrace:
    def test_valid_json_complete_events_containment(self, tmp_path):
        t = {"now": 10.0}
        rec = SpanRecorder(clock=lambda: t["now"])
        with rec.span("run", cat="command"):
            t["now"] += 1.0
            with rec.span("trials"):
                t["now"] += 2.0
                rec.fence(jax.numpy.zeros(()))
            t["now"] += 0.25
        path = rec.write_chrome_trace(str(tmp_path / "trace.json"))
        trace = json.loads(open(path).read())
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert [e["name"] for e in xs] == ["run", "trials"]
        run, trials = xs
        for e in xs:  # complete events: ts + dur present, one pid/tid
            assert e["dur"] > 0 and (e["pid"], e["tid"]) == (run["pid"], 0)
        # Time containment is what makes Perfetto nest them.
        assert run["ts"] <= trials["ts"]
        assert trials["ts"] + trials["dur"] <= run["ts"] + run["dur"]
        assert trials["args"]["fenced"] is True
        assert "fenced" in trials["cat"]
        assert run["args"]["fenced"] is False

    def test_open_span_exported_with_duration_to_now(self):
        t = {"now": 0.0}
        rec = SpanRecorder(clock=lambda: t["now"])
        cm = rec.span("crashy")
        cm.__enter__()
        t["now"] += 4.0
        trace = rec.chrome_trace()
        (ev,) = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        assert ev["dur"] == pytest.approx(4.0 * 1e6)
        cm.__exit__(None, None, None)


class TestPhaseTimersView:
    def test_shared_recorder_spans_appear_in_trace(self):
        rec = SpanRecorder()
        timers = PhaseTimers(spans=rec)
        with timers.time("dispatch", chunk=0):
            pass
        assert [sp.name for sp in rec.spans] == ["dispatch"]
        assert timers.count("dispatch") == 1
        assert rec.spans[0].args == {"chunk": 0}

    def test_time_yields_span(self):
        timers = PhaseTimers()
        with timers.time("readback") as sp:
            sp.fenced = True
        assert timers.spans.spans[0].fenced


class TestWarnAndRecord:
    def test_hook_capture_and_warning(self):
        with record_decisions() as decisions:
            with pytest.warns(QBADemotionWarning, match="demoting"):
                warn_and_record(
                    "demoting to x",
                    QBADemotionWarning,
                    site="tests.here",
                    engine_from="a",
                    engine_to="b",
                )
        (rec,) = decisions
        assert rec["kind"] == "demotion"
        assert rec["category"] == "QBADemotionWarning"
        assert rec["site"] == "tests.here"
        assert (rec["engine_from"], rec["engine_to"]) == ("a", "b")
        # Hooks are removed at context exit.
        with pytest.warns(QBAProbeWarning):
            warn_and_record("probe failed", QBAProbeWarning, site="t")
        assert len(decisions) == 1

    def test_broken_hook_never_blocks_the_warning(self):
        from qba_tpu.diagnostics import add_decision_hook, remove_decision_hook

        hook = add_decision_hook(lambda rec: 1 / 0)
        try:
            with pytest.warns(QBAProbeWarning):
                warn_and_record("still warns", QBAProbeWarning, site="t")
        finally:
            remove_decision_hook(hook)


class TestManifest:
    @pytest.mark.parametrize(
        "shape", [(11, 64, 3), (33, 64, 10)], ids=["headline", "northstar"]
    )
    def test_schema_roundtrip(self, tmp_path, shape):
        p, l, d = shape
        cfg = QBAConfig(n_parties=p, size_l=l, n_dishonest=d)
        manifest = collect_manifest(cfg, command="test")
        validate_manifest(manifest)
        path = write_manifest(str(tmp_path / "m.json"), manifest)
        loaded = load_manifest(path)  # load_manifest re-validates
        assert loaded["schema"] == MANIFEST_SCHEMA
        assert loaded["plan"]["engine"] == manifest["plan"]["engine"]
        assert loaded["config"]["n_parties"] == p
        assert loaded["config"]["derived"]["n_rounds"] == d + 1
        # The chain starts at the requested engine and ends at what ran.
        assert loaded["demotion_chain"][0] == cfg.round_engine
        assert loaded["demotion_chain"][-1] in (
            "xla", "pallas", "pallas_tiled", "pallas_fused",
        )
        for key in ("before", "after", "delta"):
            assert isinstance(loaded["probe_stats"][key], dict)

    def test_validate_rejects_and_collects_all_problems(self):
        with pytest.raises(ValueError) as ei:
            validate_manifest({"schema": "wrong", "plan": []})
        msg = str(ei.value)
        assert "schema" in msg and "missing key" in msg and "plan" in msg

    def test_demotion_chain_fused_without_block(self):
        cfg = QBAConfig(n_parties=5, size_l=8, round_engine="pallas_fused")
        plan = {"engine": "pallas_fused", "fused_block": None}
        assert demotion_chain(cfg, plan) == ["pallas_fused", "pallas_tiled"]

    def test_counters_enabled_recorded(self):
        cfg = QBAConfig(n_parties=5, size_l=8, collect_counters=True)
        assert collect_manifest(cfg, command="t")["counters_enabled"] is True

    def test_telemetry_session_writes_artifacts(self, tmp_path):
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=1, trials=2)
        directory = str(tmp_path / "telemetry")
        with telemetry_session(directory, cfg, "run") as session:
            timers = PhaseTimers(spans=session.spans)
            with timers.time("trials") as sp:
                res = run_trials(cfg)
                sp.fenced = True
            session.extra["note"] = "smoke"
        manifest = load_manifest(session.manifest_path)
        assert manifest["command"] == "run" and manifest["note"] == "smoke"
        assert "trials" in manifest["phase_totals"]
        trace = json.loads(open(session.trace_path).read())
        names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "X"]
        assert names == ["run", "trials"]
        assert (tmp_path / "telemetry" / "spans.jsonl").exists()
        assert int(np.asarray(res.trials.decisions).shape[0]) == 2

    def test_telemetry_session_writes_on_failure(self, tmp_path):
        cfg = QBAConfig(n_parties=5, size_l=8)
        directory = str(tmp_path / "t")
        with pytest.raises(RuntimeError):
            with telemetry_session(directory, cfg, "run") as session:
                raise RuntimeError("mid-run crash")
        load_manifest(session.manifest_path)  # still written + valid


class TestProtocolCounters:
    @pytest.mark.parametrize("engine", JIT_ENGINES)
    def test_primary_outputs_bit_identical(self, engine):
        cfg_off = QBAConfig(
            n_parties=7, size_l=16, n_dishonest=2, trials=8, seed=11,
            round_engine=engine,
        )
        cfg_on = dataclasses.replace(cfg_off, collect_counters=True)
        off, on = run_trials(cfg_off), run_trials(cfg_on)
        for field in ("decisions", "success", "vi", "overflow"):
            a = np.asarray(getattr(off.trials, field))
            b = np.asarray(getattr(on.trials, field))
            assert np.array_equal(a, b), (engine, field)
        assert off.trials.counters is None
        assert on.trials.counters is not None

    @pytest.mark.parametrize("engine", JIT_ENGINES)
    def test_counters_consistent_with_vi(self, engine):
        cfg = QBAConfig(
            n_parties=7, size_l=16, n_dishonest=2, trials=8, seed=5,
            round_engine=engine, collect_counters=True,
        )
        res = run_trials(cfg)
        c = res.trials.counters
        vi = np.asarray(res.trials.vi)
        first = np.asarray(c.first_accept_round)
        # A (receiver, value) was accepted iff it has a first-accept
        # round; rounds are 0 (step 3a) .. n_rounds.
        assert np.array_equal(first >= 0, vi)
        assert first.max() <= cfg.n_rounds
        assert np.array_equal(
            np.asarray(c.accept_counts), vi.sum(axis=-2)
        )
        # Per-round accepts total the post-step-3a acceptances.
        step3a = int((first == 0).sum())
        assert int(np.asarray(c.accepts_per_round).sum()) == int(
            vi.sum() - step3a
        )
        assert np.asarray(c.slot_high_water).min() >= 0
        assert np.asarray(c.overflow_rounds).shape == (
            cfg.trials, cfg.n_rounds,
        )
        # Any per-round overflow must surface in the trial overflow flag.
        assert np.array_equal(
            np.asarray(c.overflow_rounds).any(axis=-1)
            | ~np.asarray(res.trials.overflow),
            np.ones(cfg.trials, bool),
        ) or not np.asarray(res.trials.overflow).any()

    def test_counters_identical_across_engines(self):
        results = {}
        for engine in JIT_ENGINES:
            cfg = QBAConfig(
                n_parties=7, size_l=16, n_dishonest=2, trials=8, seed=3,
                round_engine=engine, collect_counters=True,
            )
            results[engine] = run_trials(cfg).trials.counters
        ref = results["xla"]
        for engine in JIT_ENGINES[1:]:
            for field in dataclasses.fields(ref):
                assert np.array_equal(
                    np.asarray(getattr(ref, field.name)),
                    np.asarray(getattr(results[engine], field.name)),
                ), (engine, field.name)

    def test_packed_fused_counters_match_unpacked(self):
        base = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=1, trials=8, seed=7,
            round_engine="pallas_fused", collect_counters=True,
        )
        packed = run_trials(dataclasses.replace(base, trial_pack=2))
        plain = run_trials(dataclasses.replace(base, trial_pack=1))
        for field in dataclasses.fields(plain.trials.counters):
            assert np.array_equal(
                np.asarray(getattr(packed.trials.counters, field.name)),
                np.asarray(getattr(plain.trials.counters, field.name)),
            ), field.name
        assert np.array_equal(
            np.asarray(packed.trials.decisions),
            np.asarray(plain.trials.decisions),
        )
