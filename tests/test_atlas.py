"""Atlas-subsystem tests (docs/ATLAS.md).

Five contracts:

* **Cube determinism** — :func:`enumerate_cells` is a pure function of
  the :class:`CampaignSpec`: same spec, same cells in the same order,
  deduped, with content addresses derived from per-cell config
  fingerprints (trials excluded — chunk sizing is not identity).
* **Content addressing** — the store files every record under the hash
  of its own config; dialect differences (``trials``/``derived``)
  collapse to one key; a different config under the same filename is
  an :class:`AtlasCollision`, never an overwrite; the store digest
  covers exactly the identity view (manifests/provenance excluded).
* **Cache reads** — :meth:`AtlasStore.lookup` answers a config+target
  query from a certified record: a decided stop at the same threshold
  certifies even when the conservative anytime CI straddles it
  (e-value decisions fire first); weaker questions hit on stronger
  certificates; everything else misses.
* **Campaign determinism** — a driver kill (result-budget interrupt or
  a fleet worker SIGKILL) followed by resume-from-ledger yields a
  store digest bit-identical to the uninterrupted run: at-least-once
  delivery + idempotent, content-addressed publication = exactly-once
  effect.
* **KI-11 completeness** (docs/KNOWN_ISSUES.md) — the lint re-derives
  the cube from the ledger's spec and proves every cell terminal with
  an honest record; tampered stores (deleted record, truncation
  mis-marked as certified, config drift) produce findings.
"""

import dataclasses
import json
import os

import pytest

from qba_tpu.atlas.cube import (
    CampaignSpec,
    attempt_trials,
    build_request,
    enumerate_cells,
    parse_dishonest,
    request_id_for,
    resolve_dishonest,
)
from qba_tpu.atlas.store import (
    CELL_SCHEMA,
    AtlasCollision,
    AtlasStore,
    cell_key,
    cell_slug,
    record_satisfies,
    validate_cell_record,
)

def _spec(**kw):
    kw.setdefault("parties", (4,))
    kw.setdefault("dishonest", (1,))
    kw.setdefault("chunk_trials", 32)
    kw.setdefault("budget_trials", 64)
    kw.setdefault("max_escalations", 1)
    kw.setdefault("target", "decide vs 1/3 @ 95%")
    return CampaignSpec(**kw)


# ---- cube enumeration --------------------------------------------------


def test_campaign_spec_roundtrips_and_keys_stably():
    spec = _spec(parties=(4, 7), dishonest=(1, 1 / 3),
                 noise_points=((0.0, 0.0), (0.05, 0.02)))
    again = CampaignSpec.from_json(spec.to_json())
    assert again == spec
    assert again.campaign_key() == spec.campaign_key()
    # key is a pure function of the spec content
    assert _spec().campaign_key() != spec.campaign_key()


def test_parse_and_resolve_dishonest():
    assert parse_dishonest(["1", "1/3", "0.5"]) == (1.0, 1 / 3, 0.5)
    # fractions floor per party count; duplicates collapse; counts
    # exceeding n-1 are skipped for that n
    assert resolve_dishonest(7, (1 / 3, 2.0)) == [2]
    assert resolve_dishonest(4, (1 / 3, 1.0)) == [1]
    assert resolve_dishonest(4, (9.0,)) == []


def test_enumerate_cells_deterministic_deduped_content_addressed():
    spec = _spec(parties=(4, 7), dishonest=(1, 1 / 4))
    cells = enumerate_cells(spec)
    again = enumerate_cells(spec)
    assert [c.key for c in cells] == [c.key for c in again]
    assert len({c.key for c in cells}) == len(cells)  # deduped
    for c in cells:
        # the address is the fingerprint hash, independent of trials
        # (chunk sizing is execution policy, not identity)
        assert c.key == cell_key(c.fingerprint)
        fp_with_trials = dict(c.fingerprint)
        fp_with_trials["trials"] = 999_999
        assert cell_key(fp_with_trials) == c.key


def test_attempt_trials_escalates_in_whole_chunks():
    spec = _spec(chunk_trials=32, budget_trials=48, escalation=4.0)
    assert attempt_trials(spec, 0) % 32 == 0
    assert attempt_trials(spec, 0) >= 48
    assert attempt_trials(spec, 1) >= 4 * 48
    assert attempt_trials(spec, 1) % 32 == 0


def test_build_request_carries_target_and_stable_ids():
    spec = _spec()
    (cell,) = enumerate_cells(spec)
    req = build_request(cell, spec, 0)
    assert req.request_id == request_id_for(cell.key, 0)
    assert req.target == spec.target
    assert req.trials == attempt_trials(spec, 0)
    assert request_id_for(cell.key, 1) != req.request_id


# ---- store addressing --------------------------------------------------


def _fp(**kw):
    fp = {"n_parties": 4, "size_l": 4, "n_dishonest": 1, "seed": 0,
          "strategy": "reference", "p_depolarize": 0.0,
          "p_measure_flip": 0.0}
    fp.update(kw)
    return fp


def _record(fp, status="certified", stop_reason="decided_above",
            lo=0.5, hi=0.9, **kw):
    rec = {
        "schema": CELL_SCHEMA,
        "cell_key": cell_key(fp),
        "coords": {k: fp.get(k) for k in (
            "strategy", "p_depolarize", "p_measure_flip", "size_l",
            "n_parties", "n_dishonest")},
        "config": dict(fp),
        "target": "decide vs 1/3 @ 95%",
        "chunk_trials": 32,
        "status": status,
        "stop": {
            "reason": stop_reason, "threshold": 1 / 3, "n_trials": 64,
        } if stop_reason else None,
        "ci": {"rate": (lo + hi) / 2, "lo": lo, "hi": hi,
               "confidence": 0.95},
        "successes": 40,
        "n_trials": 64,
        "attempts": 1,
        "refusal": ({"reason": "budget_exhausted"}
                    if status == "refused" else None),
    }
    rec.update(kw)
    return rec


def test_cell_key_collapses_fingerprint_dialects():
    fp = _fp()
    assert cell_key(fp) == cell_key({**fp, "trials": 123})
    assert cell_key(fp) == cell_key({**fp, "derived": {"w": 9}})
    assert cell_key(fp) != cell_key(_fp(seed=1))
    assert cell_slug(fp) == f"cell-{cell_key(fp)}"


def test_store_write_load_lookup_and_collision(tmp_path):
    store = AtlasStore(str(tmp_path / "atlas"))
    fp = _fp()
    rec = _record(fp)
    path = store.write_cell(rec)
    assert os.path.basename(path) == cell_slug(fp) + ".json"
    assert store.load_cell(rec["cell_key"]) == rec
    # lookup: hit at the certified target, miss for a config not there
    assert store.lookup(fp, "decide vs 1/3 @ 95%") == rec
    assert store.lookup(fp) == rec
    assert store.lookup(_fp(seed=5)) is None
    # re-certifying the same config overwrites in place
    store.write_cell(_record(fp, lo=0.6, hi=0.8))
    assert store.load_cell(rec["cell_key"])["ci"]["lo"] == 0.6
    # ... but a filename already holding a *different* config (e.g. a
    # truncated-hash forgery or on-disk tampering) is refused loudly
    tampered = json.load(open(store.cell_path(rec["cell_key"])))
    tampered["config"]["seed"] = 77
    with open(store.cell_path(rec["cell_key"]), "w") as f:
        json.dump(tampered, f)
    with pytest.raises(AtlasCollision):
        store.write_cell(rec)


def test_record_satisfies_stop_certificate_beats_straddling_ci():
    fp = _fp()
    # e-value rule decided above 1/3 but the conservative anytime CI
    # still straddles the threshold: the decision is the certificate.
    rec = _record(fp, lo=0.3329, hi=0.7230)
    assert record_satisfies(rec, "decide vs 1/3 @ 95%")
    # a different threshold falls back to the CI test: excluded by the
    # CI answers anyway; inside the CI misses
    assert record_satisfies(rec, "decide vs 3/4 @ 95%")
    assert not record_satisfies(rec, "decide vs 1/2 @ 95%")
    # width questions need the CI to actually be tight
    assert not record_satisfies(rec, "ci_width<=0.05 @ 95%")
    assert record_satisfies(_record(fp, lo=0.80, hi=0.84),
                            "ci_width<=0.05 @ 95%")
    # higher-confidence questions than the certificate answers: miss
    assert not record_satisfies(
        _record(fp, lo=0.5, hi=0.9), "decide vs 1/3 @ 99%")
    assert not record_satisfies(_record(fp, status="refused"),
                                "decide vs 1/3 @ 95%")


def test_store_digest_covers_identity_not_provenance(tmp_path):
    a = AtlasStore(str(tmp_path / "a"))
    b = AtlasStore(str(tmp_path / "b"))
    fp = _fp()
    a.write_cell(_record(fp, manifest={"engine": "xla"},
                         provenance={"replica_id": "r0"}))
    b.write_cell(_record(fp, manifest={"engine": "pallas"},
                         provenance={"replica_id": "r7"}))
    assert a.digest() == b.digest()
    b.write_cell(_record(_fp(seed=3)))
    assert a.digest() != b.digest()


def test_validate_cell_record_rejects_dishonest_certificates():
    fp = _fp()
    with pytest.raises(ValueError, match="schema"):
        validate_cell_record({**_record(fp), "schema": "nope/v0"})
    with pytest.raises(ValueError, match="content-address"):
        validate_cell_record({**_record(fp), "cell_key": "f" * 16})
    # a truncation mis-marked as certified: budget_exhausted cannot
    # certify a target (the KI-11 negative fixture)
    with pytest.raises(ValueError, match="budget_exhausted"):
        validate_cell_record(
            _record(fp, stop_reason="budget_exhausted"))
    with pytest.raises(ValueError, match="refusal"):
        validate_cell_record(
            {**_record(fp, status="refused"), "refusal": None})
    with pytest.raises(ValueError, match="lo/hi"):
        validate_cell_record(
            {**_record(fp), "ci": {"rate": 0.5}})


# ---- local campaign end-to-end -----------------------------------------


def _run_campaign(store_dir, spec, cache_dir, **driver_kw):
    from qba_tpu.atlas.campaign import CampaignDriver, LocalExecutor

    store = AtlasStore(store_dir)
    driver = CampaignDriver(
        store, spec,
        LocalExecutor(chunk_trials=spec.chunk_trials,
                      cache_dir=cache_dir),
        **driver_kw,
    )
    return store, driver.run()


def test_local_campaign_certifies_cube_and_passes_ki11(tmp_path):
    from qba_tpu.analysis.atlas import check_atlas_store

    spec = _spec(parties=(4, 5), dishonest=(0, 1))
    store, summary = _run_campaign(
        str(tmp_path / "atlas"), spec, str(tmp_path / "cache"))
    assert summary["open"] == 0
    assert summary["cells"] == 4
    assert summary["certified"] + summary["refused"] == 4
    assert not summary["interrupted"]
    report = check_atlas_store(store.root)
    assert report.ok, report.render()
    assert report.stats["atlas_cells"] == 4
    atlas = json.load(open(store.atlas_path))
    assert atlas["schema"] == "qba-tpu/atlas/v1"
    (sl,) = atlas["slices"]
    assert len(sl["points"]) == 4
    # the KI-7 fence is measured from the honest-baseline cells
    assert atlas["ki7_fence"], "d=0 cells must produce a measured fence"
    for curve in atlas["ki7_fence"]:
        for pt in curve["points"]:
            assert pt["lo"] is None or pt["lo"] <= pt["hi"]


def test_campaign_resume_differential_is_bit_identical(tmp_path):
    spec = _spec(parties=(4, 5), dishonest=(1,))
    cache = str(tmp_path / "cache")
    ref_store, ref = _run_campaign(str(tmp_path / "ref"), spec, cache)
    assert ref["open"] == 0

    # interrupt after one processed result (the driver-kill story: the
    # ledger survives, in-flight work is re-admitted on resume)
    store_b, first = _run_campaign(
        str(tmp_path / "b"), spec, cache, max_results=1)
    assert first["interrupted"]
    assert first["open"] >= 1
    store_b2, second = _run_campaign(str(tmp_path / "b"), spec, cache)
    assert second["open"] == 0
    assert not second["interrupted"]
    assert second["store_digest"] == ref["store_digest"]


def test_campaign_ledger_refuses_foreign_spec(tmp_path):
    from qba_tpu.atlas.campaign import CampaignDriver, LocalExecutor

    spec = _spec()
    store, summary = _run_campaign(
        str(tmp_path / "atlas"), spec, str(tmp_path / "cache"))
    assert summary["open"] == 0
    other = _spec(parties=(5,))
    with pytest.raises(ValueError, match="campaign"):
        CampaignDriver(store, other, LocalExecutor()).run()


# ---- KI-11 tampering fixtures ------------------------------------------


def test_ki11_catches_tampered_stores(tmp_path):
    from qba_tpu.analysis.atlas import check_atlas_store

    spec = _spec(parties=(4, 5), dishonest=(1,))
    store, summary = _run_campaign(
        str(tmp_path / "atlas"), spec, str(tmp_path / "cache"))
    assert check_atlas_store(store.root).ok

    # (a) delete a certified record: the ledger's claim is unbacked
    victim = next(iter(json.load(open(store.ledger_path))["cells"]))
    os.unlink(store.cell_path(victim))
    rep = check_atlas_store(store.root)
    assert any(f.check == "record-missing" for f in rep.findings)

    # (b) mark an enumerated cell non-terminal: campaign incomplete
    led = json.load(open(store.ledger_path))
    led["cells"][victim]["status"] = "submitted"
    with open(store.ledger_path, "w") as f:
        json.dump(led, f)
    rep = check_atlas_store(store.root)
    assert any("neither certified" in f.message for f in rep.findings)

    # (c) drift a surviving record's config: content-address violation
    other = next(k for k in led["cells"] if k != victim)
    rec = json.load(open(store.cell_path(other)))
    rec["config"]["seed"] = 999
    with open(store.cell_path(other), "w") as f:
        json.dump(rec, f)
    rep = check_atlas_store(store.root)
    assert any(f.check in ("record-invalid", "content-address")
               for f in rep.findings)


def test_ki11_requires_a_ledger(tmp_path):
    from qba_tpu.analysis.atlas import check_atlas_store

    store = AtlasStore(str(tmp_path / "bare"))
    store.write_cell(_record(_fp()))
    rep = check_atlas_store(store.root)
    assert any(f.check == "ledger-missing" for f in rep.findings)


# ---- content-addressed surface checkpoints (compat shim) ---------------


def test_surface_checkpoints_content_addressed_with_legacy_shim(tmp_path):
    from qba_tpu.config import QBAConfig
    from qba_tpu.sweep import _config_fingerprint, run_surface

    cfg = QBAConfig(n_parties=4, size_l=4, n_dishonest=1, trials=16,
                    seed=3)
    ckdir = str(tmp_path / "ck")
    kw = dict(strategies=["reference"], noise_points=[(0.0, 0.0)],
              size_ls=[4], n_chunks=2, chunk_trials=16,
              checkpoint_dir=ckdir)
    (cell,) = run_surface(cfg, **kw)
    cfg_cell = dataclasses.replace(cfg, strategy="reference",
                                   p_depolarize=0.0,
                                   p_measure_flip=0.0, size_l=4)
    addressed = os.path.join(
        ckdir, cell_slug(_config_fingerprint(cfg_cell)) + ".json")
    assert os.path.exists(addressed)

    # resume from the addressed file
    (resumed,) = run_surface(cfg, **kw)
    assert resumed.result.resumed_chunks == 2
    assert resumed.result.success_rate == cell.result.success_rate

    # a pre-atlas checkpoint dir keeps resuming via its legacy name
    legacy = os.path.join(ckdir, "surface_reference_p0.0_q0.0_L4.json")
    os.replace(addressed, legacy)
    (shimmed,) = run_surface(cfg, **kw)
    assert shimmed.result.resumed_chunks == 2
    assert shimmed.result.success_rate == cell.result.success_rate


def test_run_surface_publishes_atlas_records(tmp_path):
    from qba_tpu.analysis.atlas import check_atlas_store
    from qba_tpu.config import QBAConfig
    from qba_tpu.sweep import _config_fingerprint, run_surface

    cfg = QBAConfig(n_parties=4, size_l=4, n_dishonest=1, trials=32,
                    seed=3)
    sdir = str(tmp_path / "atlas")
    run_surface(cfg, strategies=["reference"],
                noise_points=[(0.0, 0.0)], size_ls=[4], n_chunks=2,
                chunk_trials=32, target="decide vs 1/3 @ 95%",
                store_dir=sdir)
    store = AtlasStore(sdir)
    cfg_cell = dataclasses.replace(cfg, strategy="reference",
                                   p_depolarize=0.0,
                                   p_measure_flip=0.0, size_l=4)
    rec = store.load_cell(cell_key(_config_fingerprint(cfg_cell)))
    assert rec is not None
    validate_cell_record(rec)
    assert rec["status"] in ("certified", "refused")
    assert rec["provenance"]["producer"] == "run_surface"
    # no-target runs publish uncertified estimates (KI-8: never a bare
    # rate) — and a ledgerless store is a collection, not an atlas
    run_surface(cfg, strategies=["reference"],
                noise_points=[(0.0, 0.0)], size_ls=[4], n_chunks=1,
                chunk_trials=32, store_dir=str(tmp_path / "untgt"))
    (name, urec), = AtlasStore(str(tmp_path / "untgt")).iter_cells()
    assert urec["status"] == "uncertified"
    assert not check_atlas_store(str(tmp_path / "untgt")).ok


# ---- fleet campaign: worker SIGKILL ------------------------------------


@pytest.mark.slow
def test_fleet_campaign_survives_worker_sigkill(tmp_path):
    """The acceptance story in miniature: a 2-replica supervised fleet
    runs the campaign, one worker is SIGKILLed mid-stream, and the
    campaign still certifies the whole cube with a store digest equal
    to a clean local run (zero lost, zero duplicated cells)."""
    import threading

    from qba_tpu.analysis.atlas import check_atlas_store
    from qba_tpu.atlas.campaign import CampaignDriver, FleetExecutor
    from qba_tpu.serve.fleet import AdmissionController, ReplicaPool

    spec = _spec(parties=(4, 5), dishonest=(0, 1))
    ref_store, ref = _run_campaign(
        str(tmp_path / "ref"), spec, str(tmp_path / "cache"))
    assert ref["open"] == 0

    qdir = str(tmp_path / "q")
    pool = ReplicaPool(qdir, replicas=2, chunk_trials=spec.chunk_trials,
                       reclaim_timeout_s=20.0, poll_s=0.02,
                       cache_dir=str(tmp_path / "cache"))
    pool.start()
    killed = {}

    def chaos(i, payload):
        if not killed:
            alive = pool.alive()
            if alive:
                killed["pid"] = pool.kill(alive[-1])

    store = AtlasStore(str(tmp_path / "fleet"))
    driver = CampaignDriver(
        store, spec, FleetExecutor(qdir),
        admission=AdmissionController(chunk_trials=spec.chunk_trials,
                                      replicas=2),
        on_result=chaos, idle_timeout_s=240.0,
    )
    try:
        summary = driver.run()
    finally:
        pool.stop()
    assert killed, "chaos hook never fired"
    assert summary["open"] == 0
    assert summary["store_digest"] == ref["store_digest"]
    report = check_atlas_store(store.root)
    assert report.ok, report.render()
