"""Fused Pallas round kernel vs the XLA round engine.

The kernel (:mod:`qba_tpu.ops.round_kernel`) must produce bit-identical
verdicts (accepted sets, decisions, overflow flags) to the XLA path for
the same trial keys — both consume the same batched attack draws.  Runs
in interpreter mode on the CPU test mesh; the same kernel compiles for
real on TPU (``round_engine="auto"``).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBAProbeWarning
from qba_tpu.rounds import run_trial


def both(cfg, seed, n):
    keys = jax.random.split(jax.random.key(seed), n)
    xla_cfg = dataclasses.replace(cfg, round_engine="xla")
    pal_cfg = dataclasses.replace(cfg, round_engine="pallas")
    a = jax.jit(jax.vmap(lambda k: run_trial(xla_cfg, k)))(keys)
    b = jax.jit(jax.vmap(lambda k: run_trial(pal_cfg, k)))(keys)
    return a, b


def assert_equal(a, b):
    assert a.vi.tolist() == b.vi.tolist()
    assert a.decisions.tolist() == b.decisions.tolist()
    assert a.success.tolist() == b.success.tolist()
    assert a.overflow.tolist() == b.overflow.tolist()


class TestKernelEquivalence:
    def test_all_honest(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=0)
        assert_equal(*both(cfg, 0, 8))

    def test_adversarial(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        a, b = both(cfg, 1, 16)
        assert_equal(a, b)
        # the batch must actually exercise dishonest behavior
        assert not bool(jnp.all(a.honest))

    def test_wide_positions_single_receiver_group(self):
        # size_l >= 128 -> _lane_group == 1: the degenerate per-receiver
        # case must flow through the same lane-packed algebra unchanged.
        from qba_tpu.ops.round_kernel import _lane_group

        cfg = QBAConfig(n_parties=4, size_l=128, n_dishonest=1)
        assert _lane_group(cfg.size_l, cfg.n_lieutenants) == 1
        assert_equal(*both(cfg, 5, 4))

    def test_tail_overlap_group(self):
        # n_lieutenants not divisible by the group size: the tail group
        # re-covers already-processed receivers; vi must not double-update.
        from qba_tpu.ops.round_kernel import _lane_group

        cfg = QBAConfig(n_parties=6, size_l=48, n_dishonest=2)
        assert _lane_group(cfg.size_l, cfg.n_lieutenants) == 2
        assert cfg.n_lieutenants % 2 == 1
        assert_equal(*both(cfg, 6, 8))

    def test_racy_delivery(self):
        cfg = QBAConfig(
            n_parties=4, size_l=8, n_dishonest=1, delivery="racy", p_late=0.5
        )
        assert_equal(*both(cfg, 2, 16))

    def test_tight_slot_bound_overflow(self):
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_accepts_per_round=1
        )
        a, b = both(cfg, 3, 16)
        assert_equal(a, b)

    def test_larger_config(self):
        cfg = QBAConfig(n_parties=7, size_l=32, n_dishonest=2)
        assert_equal(*both(cfg, 4, 8))

    def test_config_validation(self):
        with pytest.raises(ValueError):
            QBAConfig(n_parties=3, size_l=4, round_engine="cuda")


class TestEngineSelection:
    def test_vmem_prefilter(self):
        # fits_kernel is the loose pre-filter in front of the compile
        # probe: plausible configs pass (the probe decides), hopeless
        # ones (the reference's sizeL=1000 at the lossless slot bound,
        # observed compile OOM on TPU) are rejected without paying for a
        # doomed compile.
        from qba_tpu.ops.round_kernel import fits_kernel

        assert fits_kernel(QBAConfig(n_parties=11, size_l=64, n_dishonest=3))
        assert fits_kernel(
            QBAConfig(
                n_parties=33, size_l=64, n_dishonest=10,
                max_accepts_per_round=4,
            )
        )
        # The reference's sizeL=1000 at the lossless bound now passes
        # the pre-filter (and compiles, with the raised vmem cap —
        # docs/PERF.md round 3); the hopeless case is the 33-party
        # lossless mailbox, whose whole-mailbox-in-VMEM working set is
        # beyond physical VMEM (the tiled engine owns that config).
        assert fits_kernel(QBAConfig(n_parties=11, size_l=1000, n_dishonest=5))
        assert not fits_kernel(
            QBAConfig(n_parties=33, size_l=64, n_dishonest=10)
        )

    @pytest.fixture
    def clean_probe_cache(self):
        import qba_tpu.ops.round_kernel as rk

        rk._PROBE_CACHE.clear()
        yield rk
        rk._PROBE_CACHE.clear()

    def test_probe_skipped_when_prefiltered(self, monkeypatch, clean_probe_cache):
        # A config outside the pre-filter must return False without
        # attempting a compile — loudly (ADVICE r2: a silent engine
        # downgrade from the unreliable estimate must be observable).
        rk = clean_probe_cache

        def boom(*a, **k):  # pragma: no cover - failure path
            raise AssertionError("probe compiled a prefiltered config")

        monkeypatch.setattr(rk, "build_round_step", boom)
        cfg = QBAConfig(n_parties=33, size_l=64, n_dishonest=10)
        with pytest.warns(QBAProbeWarning, match="pre-filter rejected"):
            assert rk.kernel_compiles(cfg) is False

    def test_probe_result_cached(self, monkeypatch, clean_probe_cache):
        rk = clean_probe_cache
        calls = []
        real = rk.build_round_step

        def counting(*a, **k):
            calls.append(1)
            return real(*a, **k)

        monkeypatch.setattr(rk, "build_round_step", counting)
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1)
        # On the CPU test platform the real-TPU compile fails; the probe
        # must warn (not raise), cache the verdict, and stay silent on
        # the cached second call.
        with pytest.warns(QBAProbeWarning, match="compile probe failed"):
            first = rk.kernel_compiles(cfg)
        second = rk.kernel_compiles(cfg)
        assert first == second
        assert len(calls) == 1  # probe ran exactly once, result cached

    def test_explicit_engine_respected(self):
        from qba_tpu.rounds.engine import resolve_round_engine

        cfg = QBAConfig(n_parties=3, size_l=4, round_engine="pallas")
        assert resolve_round_engine(cfg) == "pallas"
        cfg = QBAConfig(n_parties=3, size_l=4, round_engine="xla")
        assert resolve_round_engine(cfg) == "xla"
