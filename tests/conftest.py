"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip TPU hardware is not available in CI; sharding tests run on
virtual CPU devices exactly as the driver's dryrun does.
"""

import os

# Force CPU: the ambient environment may point JAX at a remote TPU tunnel
# (a sitecustomize registers the backend before any conftest runs, so the
# env var alone is not enough — the config update below is authoritative).
# Remote per-op compiles make tests orders of magnitude slower, and the
# sharding tests need the virtual 8-device CPU mesh anyway.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
