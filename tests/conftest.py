"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip TPU hardware is not available in CI; sharding tests run on
virtual CPU devices exactly as the driver's dryrun does.
"""

import os

# Force CPU: the ambient environment may point JAX at a remote TPU tunnel
# (a sitecustomize registers the backend before any conftest runs, so the
# env var alone is not enough — the config update below is authoritative).
# Remote per-op compiles make tests orders of magnitude slower, and the
# sharding tests need the virtual 8-device CPU mesh anyway.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the test suite is dominated by XLA compiles
# (single-CPU CI box); caching them across runs cuts the suite from ~10 min
# to well under one.  Repo-local (gitignored) so the cache is per-checkout,
# not a shared /tmp path another user could own or poison.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".cache", "jax")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
