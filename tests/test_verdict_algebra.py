"""The shared kernel flag algebra vs the executable spec.

:mod:`qba_tpu.ops.verdict_algebra` is the one implementation of the
batched acceptance verdict both Pallas kernels trace
(``lieu_receive``'s consistency check, ``tfg.py:289-300``).  It is plain
``jax.numpy``, so beyond the kernel equivalence suites it can be pinned
*directly* against the single-packet executable spec
:func:`qba_tpu.core.consistent.consistent_after_append` on randomized
evidence — including adversarial states (cleared rows, out-of-range
values, duplicate rows) the protocol reaches only rarely.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.core.consistent import consistent_after_append
from qba_tpu.core.types import SENTINEL, Evidence
from qba_tpu.ops.round_kernel import _lane_group
from qba_tpu.ops.verdict_algebra import (
    VerdictAlgebra,
    accept_first_per_value,
)


def _random_state(rng, cfg, n_p):
    """Random packet pool + per-receiver flags, protocol-plausible but
    adversarially noisy (SENTINEL patterns, cleared rows, stray values
    at and beyond w)."""
    max_l, s, w = cfg.max_l, cfg.size_l, cfg.w
    n_rv = cfg.n_lieutenants
    li = rng.integers(0, w, size=(n_rv, s)).astype(np.int32)
    vals = np.full((max_l, n_p, s), SENTINEL, np.int32)
    lens = np.zeros((n_p, max_l), np.int32)
    count = rng.integers(0, max_l + 1, size=(n_p, 1)).astype(np.int32)
    p = (rng.random((n_p, s)) < 0.5).astype(np.int32)
    for pk in range(n_p):
        row_mask = rng.random(s) < 0.5
        for r in range(int(count[pk, 0])):
            # Mostly share the packet's P-shaped support; sometimes not.
            mask = row_mask if rng.random() < 0.7 else rng.random(s) < 0.5
            row = rng.integers(0, w + 2, size=s) - (rng.random(s) < 0.05)
            vals[r, pk, mask] = row[mask].astype(np.int32)
            lens[pk, r] = int(mask.sum()) if rng.random() < 0.8 else int(
                rng.integers(0, s + 1)
            )
    v = rng.integers(0, w, size=(n_p, 1)).astype(np.int32)
    v2 = np.where(
        rng.random((n_p, n_rv)) < 0.3,
        rng.integers(0, cfg.n_parties + 1, size=(n_p, n_rv)),
        v,
    ).astype(np.int32)
    clearp = (rng.random((n_p, n_rv)) < 0.2)
    clearl = (rng.random((n_p, n_rv)) < 0.2)
    delivered = (rng.random((n_p, n_rv)) < 0.8)
    return li, vals, lens, count, p, v2, clearp, clearl, delivered


def _spec_ok(cfg, li, vals, lens, count, p, v2, clearp, clearl,
             delivered, r_idx):
    """Reference verdict per (packet, receiver) via the single-packet
    spec: corruption applied to the evidence/P first, then
    consistent_after_append + the evidence-length acceptance rule."""
    n_p = vals.shape[1]
    n_rv = cfg.n_lieutenants
    out = np.zeros((n_p, n_rv), bool)
    for pk in range(n_p):
        for rv in range(n_rv):
            if clearl[pk, rv]:
                ev = Evidence(
                    vals=jnp.full((cfg.max_l, cfg.size_l), SENTINEL,
                                  jnp.int32),
                    lens=jnp.zeros((cfg.max_l,), jnp.int32),
                    count=jnp.asarray(0),
                )
            else:
                ev = Evidence(
                    vals=jnp.asarray(vals[:, pk]),
                    lens=jnp.asarray(lens[pk]),
                    count=jnp.asarray(count[pk, 0]),
                )
            p_mask = jnp.asarray(
                (p[pk] != 0) & (not clearp[pk, rv])
            )
            okc, new_count = consistent_after_append(
                jnp.asarray(v2[pk, rv]), ev, p_mask,
                jnp.asarray(li[rv]), cfg.w,
            )
            out[pk, rv] = bool(
                delivered[pk, rv]
                and bool(okc)
                and int(new_count) == r_idx + 1
            )
    return out


@pytest.mark.parametrize(
    "cfg,n_p",
    [
        (QBAConfig(n_parties=5, size_l=16, n_dishonest=2), 12),
        (QBAConfig(n_parties=4, size_l=48, n_dishonest=1), 8),
        # two presence planes (w = 64)
        (QBAConfig(n_parties=33, size_l=8, n_dishonest=1), 6),
    ],
    ids=("w8", "w4-tail-group", "w64-two-planes"),
)
def test_group_verdict_matches_spec(cfg, n_p):
    rng = np.random.default_rng(7)
    n_rv, s, w = cfg.n_lieutenants, cfg.size_l, cfg.w
    grp = _lane_group(s, n_rv)
    seg_l = grp * s
    r0_list = list(range(0, n_rv - grp + 1, grp))
    if n_rv % grp:
        r0_list.append(n_rv - grp)
    e = np.zeros((grp, seg_l), np.float32)
    for j in range(grp):
        e[j, j * s : (j + 1) * s] = 1.0

    for r_idx in (1, 2):
        (li, vals, lens, count, p, v2, clearp, clearl,
         delivered) = _random_state(rng, cfg, n_p)
        lip = np.stack([li[r0 : r0 + grp].reshape(-1) for r0 in r0_list])
        lioob = ((lip > w) | (lip < 0)).astype(np.int32)
        count_eff = np.where(clearl, 0, count)

        va = VerdictAlgebra(
            n_p=n_p, grp=grp, seg_l=seg_l, max_l=cfg.max_l, size_l=s,
            w=w, gdt=jnp.float32,
            vals=[jnp.asarray(vals[r]) for r in range(cfg.max_l)],
            lens=jnp.asarray(lens), count=jnp.asarray(count),
            p_i32=jnp.asarray(p), e_vals=jnp.asarray(e),
            lip_vals=jnp.asarray(lip), lioob_vals=jnp.asarray(lioob),
            r_idx=jnp.asarray(r_idx),
        )
        got = np.zeros((n_p, n_rv), bool)
        seen = set()
        for gi, r0 in enumerate(r0_list):
            sl = slice(r0, r0 + grp)
            ok_g, _, _ = va.group(
                gi, jnp.asarray(v2[:, sl]), jnp.asarray(clearp[:, sl]),
                jnp.asarray(clearl[:, sl]),
                jnp.asarray(count_eff[:, sl]),
                jnp.asarray(delivered[:, sl]),
            )
            for j in range(grp):
                if r0 + j not in seen:
                    seen.add(r0 + j)
                    got[:, r0 + j] = np.asarray(ok_g[:, j])

        want = _spec_ok(cfg, li, vals, lens, count, p, v2, clearp,
                        clearl, delivered, r_idx)
        np.testing.assert_array_equal(got, want)


def test_accept_first_per_value_semantics():
    # Sequential reference: walk packets in order, accept the first ok
    # candidate per order value not already in Vi (tfg.py:294).
    rng = np.random.default_rng(3)
    n_p, w = 24, 8
    for _ in range(20):
        ok = rng.random(n_p) < 0.5
        v2 = rng.integers(0, w, size=n_p)
        vi0 = rng.random(w) < 0.3
        want_acc = np.zeros(n_p, bool)
        vi_seq = vi0.copy()
        for i in range(n_p):
            if ok[i] and not vi_seq[v2[i]]:
                want_acc[i] = True
                vi_seq[v2[i]] = True
        acc, new_vi = accept_first_per_value(
            jnp.asarray(ok[:, None]), jnp.asarray(v2[:, None]),
            jnp.asarray(vi0[None, :].astype(np.int32)),
            jnp.arange(n_p)[:, None], n_p, w,
        )
        np.testing.assert_array_equal(np.asarray(acc[:, 0]), want_acc)
        np.testing.assert_array_equal(
            np.asarray(new_vi[0]) != 0, vi_seq
        )


def test_accept_first_per_value_group_matches_serial():
    # The group-batched variant must equal grp independent serial
    # applications of accept_first_per_value — adversarial vi/ok
    # patterns included (pre-set vi bits, all-ok, none-ok), beyond what
    # the protocol-driven kernel suites reach.
    from qba_tpu.ops.verdict_algebra import accept_first_per_value_group

    rng = np.random.default_rng(11)
    n_p, w, grp = 24, 8, 3
    for case in range(20):
        ok = rng.random((n_p, grp)) < (0.0, 0.5, 1.0)[case % 3]
        v2 = rng.integers(0, w, size=(n_p, grp))
        vi0 = (rng.random((grp, w)) < 0.3).astype(np.int32)

        class _FakeRef:
            """Row-sliceable stand-in for the kernel's ovi ref."""

            def __getitem__(self, sl):
                return jnp.asarray(vi0[sl])

        acc_cols, new_rows = accept_first_per_value_group(
            0, grp, jnp.asarray(ok), jnp.asarray(v2), _FakeRef(),
            jnp.arange(n_p)[:, None], n_p, w,
        )
        for j in range(grp):
            want_acc, want_vi = accept_first_per_value(
                jnp.asarray(ok[:, j : j + 1]),
                jnp.asarray(v2[:, j : j + 1]),
                jnp.asarray(vi0[j : j + 1, :]),
                jnp.arange(n_p)[:, None], n_p, w,
            )
            np.testing.assert_array_equal(
                np.asarray(acc_cols[j][:, 0]) != 0,
                np.asarray(want_acc[:, 0]),
            )
            np.testing.assert_array_equal(
                np.asarray(new_rows[j]) != 0, np.asarray(want_vi) != 0
            )


def test_accept_first_per_value_all_matches_serial():
    # The round-6 parallel reduction (now the accept path of every
    # kernel variant) must equal n_rv independent serial applications
    # of accept_first_per_value — including the shapes the round-4
    # group-batched pass excluded: a single receiver (grp == 1 configs)
    # and wide n_rv * w products.
    from qba_tpu.ops.verdict_algebra import accept_first_per_value_all

    rng = np.random.default_rng(13)
    for n_p, n_rv, w in ((24, 4, 8), (16, 1, 4), (8, 32, 64)):
        for case in range(12):
            ok = rng.random((n_p, n_rv)) < (0.0, 0.5, 1.0)[case % 3]
            v2 = rng.integers(0, w, size=(n_p, n_rv)).astype(np.int32)
            vi0 = (rng.random((n_rv, w)) < 0.3).astype(np.int32)
            acc, new_vi = accept_first_per_value_all(
                jnp.asarray(ok), jnp.asarray(v2), jnp.asarray(vi0),
                jnp.arange(n_p)[:, None], n_p, n_rv, w,
            )
            for r in range(n_rv):
                want_acc, want_vi = accept_first_per_value(
                    jnp.asarray(ok[:, r : r + 1]),
                    jnp.asarray(v2[:, r : r + 1]),
                    jnp.asarray(vi0[r : r + 1, :]),
                    jnp.arange(n_p)[:, None], n_p, w,
                )
                np.testing.assert_array_equal(
                    np.asarray(acc[:, r]) != 0,
                    np.asarray(want_acc[:, 0]),
                )
                np.testing.assert_array_equal(
                    np.asarray(new_vi[r : r + 1]) != 0,
                    np.asarray(want_vi) != 0,
                )


def test_accept_cross_block_carry_dependency():
    # Minimal repro of the cross-block dependency (docs/PERF.md round
    # 6): a value accepted in an earlier packet block must suppress
    # later blocks' candidates, so the per-block vi carry cannot be
    # DROPPED — but it can be REASSOCIATED: per-block first-index +
    # the vi or-merge is an associative combine, and chaining it block
    # to block (what the kernels' revisited output block does, on a
    # grid that executes sequentially anyway) recomposes the one-pass
    # answer exactly.
    from qba_tpu.ops.verdict_algebra import accept_first_per_value_all

    n_p, n_rv, w = 4, 1, 4
    ok = jnp.ones((n_p, n_rv), bool)
    v2 = jnp.zeros((n_p, n_rv), jnp.int32)  # every packet carries value 0
    vi0 = jnp.zeros((n_rv, w), jnp.int32)
    idx2 = jnp.arange(2)[:, None]
    # One pass over the whole pool: only packet 0 is accepted.
    acc_full, vi_full = accept_first_per_value_all(
        ok, v2, vi0, jnp.arange(n_p)[:, None], n_p, n_rv, w,
    )
    assert np.asarray(acc_full)[:, 0].tolist() == [1, 0, 0, 0]
    # Blocked WITHOUT the carry (each block against the initial vi):
    # block 1 also accepts its first packet — over-acceptance.
    acc_b0, vi_b0 = accept_first_per_value_all(
        ok[:2], v2[:2], vi0, idx2, 2, n_rv, w,
    )
    acc_b1_nocarry, _ = accept_first_per_value_all(
        ok[2:], v2[2:], vi0, idx2, 2, n_rv, w,
    )
    assert np.asarray(acc_b1_nocarry)[:, 0].tolist() == [1, 0]  # wrong
    # With the carry, the blocked result recomposes the one-pass answer.
    acc_b1, vi_b1 = accept_first_per_value_all(
        ok[2:], v2[2:], vi_b0, idx2, 2, n_rv, w,
    )
    assert np.asarray(acc_b0)[:, 0].tolist() == [1, 0]
    assert np.asarray(acc_b1)[:, 0].tolist() == [0, 0]
    np.testing.assert_array_equal(np.asarray(vi_b1), np.asarray(vi_full))
