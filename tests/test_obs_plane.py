"""Observability-plane tests (docs/OBSERVABILITY.md "Fleet tracing
and metrics", docs/KNOWN_ISSUES.md KI-12).

Five contracts:

* **One name table** — every metric the fleet emits is a row of
  :data:`qba_tpu.obs.metrics.METRICS`; an unregistered name or a
  mismatched label set raises at the emitter, and the rendered page is
  valid Prometheus text exposition (0.0.4) with exemplars.
* **One stitched trace per request** — a request served through the
  socket frontend + admission + two file-queue workers resolves to a
  single closed trace: intake -> admission -> queue.wait (whose
  duration IS the wire ``queue_wait_s``) -> worker spans -> settle,
  with zero orphan spans and span coverage above the KI-12 floor.
* **Crash-path closure** — a worker killed mid-request still closes
  the trace: the supervisor stamps kill/death/release/quarantine/
  settle under the request's trace id and embeds the dead worker's
  flight-recorder tail in the crash report.
* **Flight recorder** — a bounded ring flushed atomically beside the
  heartbeat; capacity trims oldest-first and the tail read is cheap.
* **KI-12 lint** — ``check_obs`` passes on the shipped tree, flags
  both seeded fixtures (a mid-request mint, an unregistered metric
  name), and ``check_span_coverage`` bites on dark time and orphans.
"""

import json
import os
import socket
import threading
import time

import pytest

from qba_tpu.obs.metrics import (
    METRICS,
    MetricsRegistry,
    default_buckets,
    validate_exposition,
)
from qba_tpu.obs.tracing import (
    TRACE_CONTEXT_SCHEMA,
    TraceEventLog,
    mint_span_id,
    mint_trace_id,
    read_trace_events,
    stitch_traces,
    stitched_chrome_trace,
    trace_summary,
)
from qba_tpu.serve import EvalRequest, QBAServer
from qba_tpu.serve.fleet import (
    AdmissionController,
    FleetFrontend,
    fleet_summary,
)
from qba_tpu.serve.queuefs import (
    FLIGHT_CAPACITY,
    FlightRecorder,
    flight_path,
    heartbeat_ages,
    read_flight_recorder,
)
from qba_tpu.serve.transport import serve_file_queue

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")


def _req(rid, n=4, L=4, d=0, trials=4, seed=0, **kw):
    return EvalRequest(
        request_id=rid, n_parties=n, size_l=L, n_dishonest=d,
        trials=trials, seed=seed, **kw,
    )


def _queue_dirs(tmp_path):
    qdir = tmp_path / "q"
    for d in ("inbox", "claimed", "done", "dead", "outbox"):
        os.makedirs(qdir / d)
    return qdir


# ---- metrics registry --------------------------------------------------


def test_registry_renders_valid_exposition_with_exemplars():
    reg = MetricsRegistry()
    reg.inc("qba_intake_requests_total", exemplar="abc123")
    reg.inc("qba_admission_decisions_total",
            labels={"action": "admit", "reason": "capacity_available"})
    reg.set_gauge("qba_queue_files", 3, labels={"box": "inbox"})
    reg.observe("qba_request_latency_seconds", 0.25)
    text = reg.render()
    assert validate_exposition(text) == []
    assert "# TYPE qba_intake_requests_total counter" in text
    assert 'qba_intake_requests_total 1 # {trace_id="abc123"} 1' in text
    assert ('qba_admission_decisions_total'
            '{action="admit",reason="capacity_available"} 1') in text
    assert 'qba_queue_files{box="inbox"} 3' in text
    # Histogram: one cumulative bucket row per default bound, +Inf,
    # then _sum and _count.
    for le in ("0.25", "+Inf"):
        assert f'qba_request_latency_seconds_bucket{{le="{le}"}} 1' in text
    assert 'qba_request_latency_seconds_bucket{le="0.1"} 0' in text
    assert "qba_request_latency_seconds_sum 0.25" in text
    assert "qba_request_latency_seconds_count 1" in text
    assert len(default_buckets()) >= 8
    assert reg.counter_value("qba_intake_requests_total") == 1.0


def test_registry_refuses_forked_names_and_label_sets():
    reg = MetricsRegistry()
    with pytest.raises(ValueError, match="unregistered metric name"):
        reg.inc("qba_frontend_retries_total")
    with pytest.raises(ValueError, match="unregistered metric name"):
        reg.set_gauge("qba_queue_depth", 1)
    # Labelled metric without its labels, and with a foreign key.
    with pytest.raises(ValueError):
        reg.inc("qba_admission_decisions_total")
    with pytest.raises(ValueError):
        reg.set_gauge("qba_queue_files", 1, labels={"bin": "inbox"})
    # Every registered row declares kind/help/labels.
    for name, (kind, help_text, label_keys) in METRICS.items():
        assert name.startswith("qba_")
        assert kind in ("counter", "gauge", "histogram")
        assert help_text
        assert isinstance(label_keys, tuple)


def test_registry_collectors_run_at_render_and_never_raise():
    reg = MetricsRegistry()
    calls = []

    def fill(r):
        calls.append(1)
        r.set_gauge("qba_fleet_replicas", 2, labels={"state": "healthy"})

    def boom(r):
        raise RuntimeError("scrape-time collectors must be fenced")

    reg.add_collector(fill)
    reg.add_collector(boom)
    text = reg.render()
    assert calls == [1]
    assert 'qba_fleet_replicas{state="healthy"} 2' in text


# ---- heartbeat staleness + flight recorder -----------------------------


def test_heartbeat_ages_reads_every_replica(tmp_path):
    from qba_tpu.serve.queuefs import heartbeat_path, write_json_atomic

    qdir = str(_queue_dirs(tmp_path))
    now = time.monotonic()
    for rid, age in (("r0", 0.5), ("r1", 7.0)):
        write_json_atomic(heartbeat_path(qdir, rid), {
            "schema": "qba-tpu/heartbeat/v1", "replica_id": rid,
            "pid": 1, "seq": 1, "phase": "idle", "request_ids": [],
            "monotonic": now - age, "stamp": 0.0,
        })
    ages = heartbeat_ages(qdir)
    assert set(ages) == {"r0", "r1"}
    assert 0.4 <= ages["r0"] < 5.0
    assert ages["r1"] >= 6.9
    assert heartbeat_ages(str(tmp_path / "nope")) == {}


def test_flight_recorder_ring_trims_and_tail_reads(tmp_path):
    qdir = str(_queue_dirs(tmp_path))
    with pytest.raises(ValueError):
        FlightRecorder(qdir, "r0", capacity=0)
    fr = FlightRecorder(qdir, "r0", capacity=4)
    for i in range(7):
        fr.note("step", i=i)
    path = flight_path(qdir, "r0")
    assert os.path.exists(path)
    payload = json.loads(open(path).read())
    assert payload["schema"] == "qba-tpu/flight-recorder/v1"
    events = read_flight_recorder(qdir, "r0")["events"]
    # Ring semantics: capacity 4 keeps the newest 4, oldest first.
    assert [e["i"] for e in events] == [3, 4, 5, 6]
    tail = read_flight_recorder(qdir, "r0", tail=2)["events"]
    assert [e["i"] for e in tail] == [5, 6]
    assert read_flight_recorder(qdir, "never-flew") is None
    assert FLIGHT_CAPACITY >= 16
    # A missing queue dir degrades the note, never the worker.
    gone = FlightRecorder(str(tmp_path / "nope" / "q"), "r9")
    gone.note("boot")  # must not raise


# ---- trace event log ---------------------------------------------------


def test_trace_event_log_round_trips_and_skips_junk(tmp_path):
    qdir = str(_queue_dirs(tmp_path))
    log = TraceEventLog(qdir)
    tid = mint_trace_id()
    rec = log.emit("intake", tid, "rq1", t=100.0)
    assert rec["schema"] == TRACE_CONTEXT_SCHEMA
    log.emit("settle", tid, "rq1", t=101.0, outcome="ok")
    with open(log.path, "a") as fh:
        fh.write("not json\n")
    events = read_trace_events(qdir)
    assert [e["event"] for e in events] == ["intake", "settle"]
    assert events[1]["outcome"] == "ok"
    assert len(tid) == 32 and len(mint_span_id()) == 16
    assert read_trace_events(str(tmp_path / "empty")) == []


# ---- end-to-end: two replicas, stitched traces, /metrics ---------------


def _worker(qdir, tel, n_requests, replica_id):
    server = QBAServer(chunk_trials=4, replica_id=replica_id,
                       telemetry_dir=str(tel))
    serve_file_queue(server, str(qdir), poll_s=0.01,
                     max_requests=n_requests)


def test_fleet_resolves_one_closed_trace_per_request(tmp_path):
    qdir = tmp_path / "q"
    tel = tmp_path / "tel"
    ac = AdmissionController(chunk_trials=4, replicas=2, window_chunks=64)
    fe = FleetFrontend(str(qdir), ac, poll_s=0.01)  # unbounded: /metrics
    workers = [
        threading.Thread(target=_worker, args=(qdir, tel, 2, "r0"),
                         daemon=True),
        threading.Thread(target=_worker, args=(qdir, tel, 1, "r1"),
                         daemon=True),
    ]
    for w in workers:
        w.start()
    port = fe.start_in_thread()
    conn = socket.create_connection(("127.0.0.1", port), timeout=120)
    wire = conn.makefile("rw")
    for rid in ("t1", "t2", "t3"):
        wire.write(json.dumps(_req(rid, trials=3, seed=7).to_json()) + "\n")
    wire.flush()
    results = [json.loads(wire.readline()) for _ in range(3)]
    for w in workers:
        w.join(timeout=120)

    def _http(raw: bytes) -> tuple[int, bytes, bytes]:
        c = socket.create_connection(("127.0.0.1", port), timeout=120)
        c.sendall(raw)
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
        c.close()
        head, _, body = buf.partition(b"\r\n\r\n")
        return int(head.split(b" ")[1]), head, body

    # Live metrics plane: valid exposition under load, typed content.
    code, head, body = _http(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
    assert code == 200
    assert b"text/plain; version=0.0.4" in head
    text = body.decode()
    assert validate_exposition(text) == []
    assert "qba_intake_requests_total 3" in text
    assert 'qba_results_forwarded_total{outcome="ok"} 3' in text
    assert "qba_request_latency_seconds_count 3" in text
    assert 'qba_replica_heartbeat_staleness_seconds{replica="r0"}' in text

    # /status carries per-replica heartbeat staleness.
    code, _, body = _http(b"GET /status HTTP/1.1\r\nHost: x\r\n\r\n")
    assert code == 200
    status = json.loads(body)
    for rid in ("r0", "r1"):
        assert status["replicas"][rid]["staleness_s"] >= 0.0
    conn.close()
    fe.stop_in_thread()

    # Every wire result carries the trace id minted at intake.
    by_id = {r["request_id"]: r for r in results}
    assert all(r["error"] is None for r in results)
    tids = {r["trace_id"] for r in results}
    assert len(tids) == 3 and None not in tids

    # One stitched trace per request, zero orphans, closed, covered.
    stitched = stitch_traces(str(qdir), telemetry_dir=str(tel))
    assert stitched["orphan_spans"] == 0
    assert set(stitched["traces"]) == tids
    for rid, res in by_id.items():
        tr = stitched["traces"][res["trace_id"]]
        assert tr["request_id"] == rid
        assert tr["closed"]
        assert tr["segments"] == 1
        assert tr["coverage"] >= 0.8  # the KI-12 floor
        names = [s["name"] for s in tr["spans"]]
        assert "request" in names and "frontend.admission" in names
        # The synthesized queue-wait span IS the wire queue_wait_s.
        (qw,) = [s for s in tr["spans"] if s["name"] == "queue.wait"]
        assert qw["dur"] == pytest.approx(res["queue_wait_s"], abs=1e-6)
        assert {e["event"] for e in tr["events"]} >= {
            "intake", "admit", "settle"}

    summary = trace_summary(stitched)
    assert summary["count"] == 3 and summary["closed"] == 3
    assert summary["orphan_spans"] == 0
    assert summary["coverage"]["min"] >= 0.8

    # The same block rides the fleet summary, and the Chrome export is
    # one renderable JSON with every span and lifecycle instant.
    fs = fleet_summary(str(qdir), telemetry_dir=str(tel))
    assert fs["traces"]["count"] == 3
    assert fs["traces"]["orphan_spans"] == 0
    chrome = stitched_chrome_trace(stitched)
    assert chrome["traceEvents"]
    assert {e["ph"] for e in chrome["traceEvents"]} >= {"X", "M", "i"}

    # Flight recorders flushed beside the heartbeats.
    for rid in ("r0", "r1"):
        flight = read_flight_recorder(str(qdir), rid)["events"]
        assert flight and flight[0]["event"] == "boot"
        assert any(e["event"] == "claim" for e in flight)


# ---- crash path: kill mid-request still closes the trace ---------------


def _write_hb(qdir, rid, pid, phase, monotonic, request_ids=()):
    from qba_tpu.serve.queuefs import heartbeat_path, write_json_atomic

    write_json_atomic(heartbeat_path(str(qdir), rid), {
        "schema": "qba-tpu/heartbeat/v1", "replica_id": rid, "pid": pid,
        "seq": 1, "phase": phase, "request_ids": list(request_ids),
        "monotonic": monotonic, "stamp": 0.0,
    })


class _FakeProc:
    def __init__(self, pid, returncode=None):
        self.pid = pid
        self.returncode = returncode

    def poll(self):
        return self.returncode


class _StubReplica:
    def __init__(self, rid, pid, returncode=None):
        self.replica_id = rid
        self.proc = _FakeProc(pid, returncode)
        self.env = {}
        self.returncode = returncode

    @property
    def alive(self):
        return self.proc.returncode is None


class _StubPool:
    def __init__(self, queue_dir, replicas):
        self.queue_dir = str(queue_dir)
        self.replicas = replicas
        self.benched = set()
        self.killed = []

    def kill(self, rid):
        for r in self.replicas:
            if r.replica_id == rid and r.alive:
                self.killed.append(rid)
                r.proc.returncode = -9
                return r.proc.pid
        raise ValueError(rid)

    def bench(self, rid):
        if rid in self.benched:
            return False
        self.benched.add(rid)
        return True

    def respawn_dead(self):
        return []


def test_killed_worker_closes_trace_with_flight_tail(tmp_path):
    from qba_tpu.serve.fleet import FleetSupervisor

    qdir = _queue_dirs(tmp_path)
    tid = mint_trace_id()
    req = _req("p1", trials=3, trace_id=tid, parent_span_id=mint_span_id())
    (qdir / "claimed" / "p1.json").write_text(json.dumps(req.to_json()))
    # The frontend's half of the lifecycle, as it would already be on
    # disk when the supervisor notices the wedge.
    log = TraceEventLog(str(qdir))
    log.emit("intake", tid, "p1")
    log.emit("admit", tid, "p1", reason="capacity_available")
    # The doomed worker's flight recorder: the last thing it did.
    fr = FlightRecorder(str(qdir), "r0")
    fr.note("claim", request_id="p1")
    fr.note("dispatch", request_id="p1", chunk=0)

    r0 = _StubReplica("r0", 100)
    r1 = _StubReplica("r1", 101)
    pool = _StubPool(qdir, [r0, r1])
    now = [1000.0]
    sup = FleetSupervisor(pool, watchdog_s=5.0, poison_threshold=2,
                          clock=lambda: now[0])
    _write_hb(qdir, "r0", 100, "dispatch", 1000.0, ["p1"])
    _write_hb(qdir, "r1", 101, "idle", 1000.0)
    now[0] = 1006.0  # r0 wedged mid-dispatch; SIGKILL path
    _write_hb(qdir, "r1", 101, "idle", 1005.5)
    step = sup.poll()
    assert step["hung_killed"] == ["r0"]
    # The release went back to the inbox under the SAME trace id.
    assert (qdir / "inbox" / "p1.json").exists()
    # Second blamed death reaches the poison threshold: quarantine.
    r1.proc.returncode = 113
    _write_hb(qdir, "r1", 101, "claim", 1006.0, ["p1"])
    now[0] = 1007.0
    sup.poll()

    res = json.loads((qdir / "outbox" / "p1.json").read_text())
    assert "quarantined as poison" in res["error"]
    assert res["trace_id"] == tid
    # The crash report embeds the blamed worker's flight-recorder tail
    # captured at death time (r1 never flew, so r0's tail survives).
    flight = res["crash_report"]["flight_recorder"]["events"]
    assert [e["event"] for e in flight] == ["claim", "dispatch"]
    assert flight[-1]["request_id"] == "p1"

    # The trace is CLOSED despite no worker result: kill, both deaths,
    # the release, the quarantine, and a settle — all under one id.
    stitched = stitch_traces(str(qdir))
    tr = stitched["traces"][tid]
    assert tr["closed"] and tr["request_id"] == "p1"
    kinds = [e["event"] for e in tr["events"]]
    for kind in ("intake", "admit", "kill", "death", "release",
                 "quarantine", "settle"):
        assert kind in kinds, kinds
    assert kinds.count("death") == 2
    assert stitched["orphan_spans"] == 0
    assert trace_summary(stitched)["closed"] == 1


# ---- KI-12 lint --------------------------------------------------------


def test_check_obs_passes_on_the_shipped_tree():
    from qba_tpu.analysis.obs import check_obs

    report = check_obs()
    assert report.ok, report.render()
    assert report.stats["obs_modules_scanned"] > 50
    assert report.stats["obs_emitter_calls_audited"] > 5
    assert report.stats["obs_mint_sites_bound"] == 2


@pytest.mark.parametrize("fixture,check", [
    ("bad_orphan_span.py", "mint-site"),
    ("bad_unregistered_metric.py", "metric-name"),
])
def test_check_obs_fixture_catches_seeded_violation(fixture, check):
    from qba_tpu.analysis.obs import check_obs_fixture

    report = check_obs_fixture(os.path.join(FIXTURES, fixture))
    assert not report.ok
    assert len(report.findings) == 1
    f = report.findings[0]
    assert f.ki == "KI-12" and f.check == check
    assert fixture in f.path


def test_check_span_coverage_bites_on_dark_time_and_orphans(tmp_path):
    from qba_tpu.analysis.obs import COVERAGE_FLOOR, check_span_coverage
    from qba_tpu.obs.telemetry import SpanRecorder

    qdir = str(_queue_dirs(tmp_path))
    log = TraceEventLog(qdir)
    tid = mint_trace_id()
    # 10 s of request lifetime, 0.1 s of admission span: dark time.
    log.emit("intake", tid, "dk1", t=100.0)
    log.emit("admit", tid, "dk1", t=100.1, reason="capacity_available")
    log.emit("settle", tid, "dk1", t=110.0)
    # An unanchored worker export: spans that stitch to no trace.
    rec = SpanRecorder()
    with rec.span("request", request_id="lost"):
        pass
    os.makedirs(os.path.join(str(tmp_path), "tel", "lost"))
    rec.write_jsonl(
        os.path.join(str(tmp_path), "tel", "lost", "spans.jsonl"))

    report = check_span_coverage(
        qdir, telemetry_dir=os.path.join(str(tmp_path), "tel"))
    assert not report.ok
    checks = [f.check for f in report.findings]
    assert checks.count("span-coverage") == 2  # orphans + dark trace
    messages = " ".join(f.message for f in report.findings)
    assert "orphan" in messages
    assert f"floor {COVERAGE_FLOOR:.0%}" in messages
    # A generous floor accepts the same data minus the orphans.
    clean = check_span_coverage(qdir, floor=0.005)
    assert clean.ok, clean.render()


# ---- atlas campaign: trace stamping + budget metrics -------------------


def test_campaign_stamps_traces_and_counts_budget(tmp_path):
    from qba_tpu.atlas.campaign import (
        CampaignDriver,
        LocalExecutor,
        _stamp_trace,
    )
    from qba_tpu.atlas.cube import CampaignSpec
    from qba_tpu.atlas.store import AtlasStore

    # _stamp_trace mints exactly once and adopts an existing context.
    stamped = _stamp_trace(_req("c0"))
    assert stamped.trace_id and stamped.parent_span_id
    assert _stamp_trace(stamped) is stamped

    spec = CampaignSpec(
        parties=(4,), dishonest=(0, 1), chunk_trials=32,
        budget_trials=64, max_escalations=1,
        target="decide vs 1/3 @ 95%",
    )
    store = AtlasStore(str(tmp_path / "atlas"))
    driver = CampaignDriver(
        store, spec,
        LocalExecutor(chunk_trials=spec.chunk_trials,
                      cache_dir=str(tmp_path / "cache")),
    )
    summary = driver.run()
    assert summary["open"] == 0
    m = summary["metrics"]
    assert m["budget_trials"] > 0
    certified = driver.metrics.counter_value(
        "qba_atlas_cells_total", {"status": "certified"})
    refused = driver.metrics.counter_value(
        "qba_atlas_cells_total", {"status": "refused"})
    assert certified + refused == summary["cells"]
    # The per-campaign registry still renders valid exposition.
    assert validate_exposition(driver.metrics.render()) == []
