"""Fused Pallas circuit kernel vs the per-gate XLA engine.

The kernel (:mod:`qba_tpu.ops.fused_circuit`) must produce the same final
state as the axis-algebra engine for every gate class it supports — lane
targets (MXU matmuls), row targets (sublane butterflies), controls
crossing the row/lane boundary, and runtime-parameterized X**b ops.  Runs
in interpreter mode on the CPU test mesh; the same kernel compiles for
real on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.qsim import generate_lists_dense
from qba_tpu.qsim.circuit import Circuit, Gate
from qba_tpu.qsim.protocol_circuits import (
    gen_nq_corr_circuit,
    gen_q_corr_circuit,
)
from qba_tpu.rounds import run_trial


def both_states(circ: Circuit, params=None):
    """(xla complex state flat, pallas-interpret real state flat)."""
    xla = circ.compile_state("xla")(params)
    pal = circ.compile_state("pallas_interpret")(params)
    return np.asarray(xla), np.asarray(pal)


def assert_states_match(circ: Circuit, params=None):
    xla, pal = both_states(circ, params)
    # Protocol gates are all real: the xla state's imaginary part is 0 and
    # amplitudes (incl. signs) must agree exactly, not just probabilities.
    np.testing.assert_allclose(xla.imag, 0.0, atol=1e-6)
    np.testing.assert_allclose(xla.real, pal, atol=1e-5)


class TestGateClasses:
    def test_lane_only_small(self):
        # n=3 (< 7): everything in the lane dimension -> pure matmul path.
        c = Circuit(3)
        g = Gate(3)
        g.add_operation("H", targets=0)
        g.add_operation("X", targets=1, controls=0)
        g.add_operation("H", targets=2)
        g.add_operation("X", targets=2, controls=(0, 1))
        c.add_operation(g)
        assert_states_match(c)

    def test_row_targets_and_cross_controls(self):
        # n=9 -> 4 rows x 128 lanes: qubits 0,1 are row qubits.
        c = Circuit(9)
        g = Gate(9)
        g.add_operation("H", targets=0)  # row target
        g.add_operation("H", targets=1)  # row target
        g.add_operation("X", targets=8, controls=0)  # row ctrl -> lane target
        g.add_operation("X", targets=1, controls=5)  # lane ctrl -> row target
        g.add_operation("H", targets=4)  # lane target
        g.add_operation("X", targets=0, controls=1)  # row ctrl -> row target
        c.add_operation(g)
        assert_states_match(c)

    @pytest.mark.parametrize("bits", [(0, 0), (1, 0), (0, 1), (1, 1)])
    def test_xpow_params_row_and_lane(self, bits):
        # n=8 -> 2 rows: qubit 0 is a row qubit, qubit 7 a lane qubit.
        c = Circuit(8)
        g = Gate(8)
        g.add_operation("H", targets=3)
        g.add_operation("XPOW", targets=0, param=0)  # row XPOW
        g.add_operation("XPOW", targets=7, param=1)  # lane XPOW
        g.add_operation("X", targets=6, controls=0)
        c.add_operation(g)
        assert_states_match(c, jnp.asarray(bits, dtype=jnp.int32))


class TestProtocolCircuits:
    @pytest.mark.parametrize("n_parties", [3, 4])
    def test_nq_circuit_matches(self, n_parties):
        nq = max(1, int(np.ceil(np.log2(n_parties + 1))))
        assert_states_match(gen_nq_corr_circuit(n_parties, nq))

    @pytest.mark.parametrize("n_parties", [3, 4])
    def test_q_circuit_matches(self, n_parties):
        nq = max(1, int(np.ceil(np.log2(n_parties + 1))))
        circ = gen_q_corr_circuit(n_parties, nq)
        perm = np.random.default_rng(0).permutation(np.arange(1, n_parties + 1))
        shifts = np.arange(nq - 1, -1, -1)
        params = ((perm[:, None] >> shifts) & 1).reshape(-1).astype(np.int32)
        assert_states_match(circ, jnp.asarray(params))

    def test_generate_lists_dense_pallas_distribution(self):
        # The pallas executor feeds the same decode path; Q-correlated
        # closed-form properties (SURVEY §2.6) must hold, AND the
        # sampled w-value distributions must match the closed form —
        # chi-square at significance 1e-4 over every party row plus a
        # binomial test on the qcorr rate (VERDICT r1 #7: test the Pallas
        # executor's *distribution*, not just its amplitudes).
        from scipy import stats

        cfg = QBAConfig(n_parties=3, size_l=512, qsim_path="dense_pallas")
        lists, qcorr = generate_lists_dense(cfg, jax.random.key(0), impl="auto")
        lists, qcorr = np.asarray(lists), np.asarray(qcorr)
        for k in range(cfg.size_l):
            col = lists[:, k]
            if qcorr[k]:
                assert len(set(col.tolist())) == cfg.n_parties + 1
            else:
                assert col[0] == col[1]
        assert (
            stats.binomtest(int(qcorr.sum()), cfg.size_l, 0.5).pvalue > 1e-4
        )
        for row in lists:
            obs = np.bincount(row, minlength=cfg.w)
            assert stats.chisquare(obs).pvalue > 1e-4

    def test_trial_on_dense_pallas_path(self):
        cfg = QBAConfig(
            n_parties=3, size_l=8, n_dishonest=0, qsim_path="dense_pallas"
        )
        r = run_trial(cfg, jax.random.key(1))
        assert bool(r.success)
        assert bool(jnp.all(r.decisions == r.v_comm))
