"""Fused single-pass round engine vs the two-kernel tiled + XLA engines.

The fused engine (:func:`qba_tpu.ops.round_kernel_tiled
.build_fused_round_kernel`) runs verdict and rebuild in ONE
``pallas_call`` per round — each grid step drains its pool block and
writes the rebuilt successor pool directly, so the intermediate
``acc``/``vi`` HBM round-trip and the second kernel launch disappear.
It must stay bit-identical to both the two-kernel tiled path (the probe
-demotion target) and the XLA oracle for the same trial keys, at every
shape class the tiled suite pins: the headline 11p/64, the ``grp == 1``
window (sizeL >= 128), the wide-group window (33p/sizeL=8, ``grp * w >
512``), and the north-star 33p/64/10.  Trial packing (``k`` trials per
kernel grid) is per-trial independent, so the packed runner is pinned
trial-for-trial against the unpacked vmap.  Runs in interpreter mode on
the CPU test mesh; the same kernel compiles for real on TPU (``auto``
prefers it wherever both it and the tiled plan compile).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBADemotionWarning
from qba_tpu.rounds import run_trial


def triad(cfg, seed, n, blk):
    """(xla, tiled, fused) trial batches for the same keys."""
    keys = jax.random.split(jax.random.key(seed), n)
    out = []
    for engine in ("xla", "pallas_tiled", "pallas_fused"):
        ecfg = dataclasses.replace(
            cfg, round_engine=engine, tiled_block=blk
        )
        out.append(jax.jit(jax.vmap(lambda k: run_trial(ecfg, k)))(keys))
    return out


def assert_equal(a, b):
    assert a.vi.tolist() == b.vi.tolist()
    assert a.decisions.tolist() == b.decisions.tolist()
    assert a.success.tolist() == b.success.tolist()
    assert a.overflow.tolist() == b.overflow.tolist()


class TestFusedEquivalence:
    def test_headline_shape(self):
        # 11p/64 — the headline benchmark config (BASELINE.json), small
        # trial count for CI.  n_pool = 10 * 16 = 160.
        cfg = QBAConfig(n_parties=11, size_l=64, n_dishonest=3)
        xla, tiled, fused = triad(cfg, 0, 2, 32)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)

    def test_adversarial_multiblock(self):
        # Multi-block verdict sweep + multi-step rebuild grid with
        # Byzantine traffic; overflow and vi must match bit for bit.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        xla, tiled, fused = triad(cfg, 1, 8, 8)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)
        assert not bool(jnp.all(xla.honest))

    def test_grp1_window(self):
        # grp == 1 (sizeL >= 128): one receiver fills a lane tile, the
        # window the round-4 group dedup excluded
        # (test_parallel_accept_outside_group_window's first shape).
        from qba_tpu.ops.round_kernel import _lane_group

        cfg = QBAConfig(n_parties=4, size_l=128, n_dishonest=1)
        assert _lane_group(cfg.size_l, cfg.n_lieutenants) == 1
        xla, tiled, fused = triad(cfg, 5, 4, 8)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)

    def test_wide_group_window(self):
        # grp * w > 512 (33p/sizeL=8: grp=16, w=64 -> 1024 lanes) — the
        # other excluded window, plus two value-presence planes.
        from qba_tpu.ops.round_kernel import _lane_group

        cfg = QBAConfig(n_parties=33, size_l=8, n_dishonest=2)
        grp = _lane_group(cfg.size_l, cfg.n_lieutenants)
        assert grp * cfg.w > 512
        xla, tiled, fused = triad(cfg, 8, 2, 64)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)

    def test_tight_slot_bound_overflow(self):
        # slots=1: the fused kernel's in-pass overflow detection (the
        # packet-major prefix sum) must reproduce the tiled/XLA flag.
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_accepts_per_round=1
        )
        xla, tiled, fused = triad(cfg, 3, 16, 4)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)

    @pytest.mark.slow
    def test_north_star_bit_identical(self):
        # The 33p/64/10 gate config (BASELINE.md config 5), 2 trials.
        # Minutes in CPU interpret mode — the tier-1 run filters
        # `-m 'not slow'`; run explicitly via `pytest -m slow`.
        cfg = QBAConfig(n_parties=33, size_l=64, n_dishonest=10)
        xla, tiled, fused = triad(cfg, 9, 2, 128)
        assert_equal(xla, fused)
        assert_equal(tiled, fused)


class TestTrialPacking:
    def test_packed_matches_unpacked(self):
        # k=2 packing folds trial pairs into one kernel grid; per-trial
        # independence must make it invisible — same keys, same
        # decisions, trial for trial.
        from qba_tpu.rounds.engine import run_trials_fused_packed

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2,
            round_engine="pallas_fused", tiled_block=16, trial_pack=2,
        )
        keys = jax.random.split(jax.random.key(11), 4)
        packed = run_trials_fused_packed(cfg, keys, 2)
        unpacked = jax.vmap(lambda k: run_trial(cfg, k))(keys)
        assert_equal(unpacked, packed)

    def test_packed_matches_xla(self):
        # And against the independent oracle, k=4 over 8 trials.
        from qba_tpu.rounds.engine import run_trials_fused_packed

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2,
            round_engine="pallas_fused", tiled_block=16, trial_pack=4,
        )
        keys = jax.random.split(jax.random.key(13), 8)
        packed = run_trials_fused_packed(cfg, keys, 4)
        xla_cfg = dataclasses.replace(cfg, round_engine="xla")
        oracle = jax.vmap(lambda k: run_trial(xla_cfg, k))(keys)
        assert_equal(oracle, packed)

    def test_run_trials_dispatch_packed(self):
        # The backend entry point routes through the packed runner when
        # the fused engine resolves with k > 1 dividing the batch — and
        # the Monte-Carlo aggregate is unchanged.
        from qba_tpu.backends.jax_backend import run_trials

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, trials=4,
            round_engine="pallas_fused", tiled_block=16, trial_pack=2,
        )
        res = run_trials(cfg)
        ref = run_trials(dataclasses.replace(cfg, round_engine="xla"))
        assert_equal(ref.trials, res.trials)
        assert float(res.success_rate) == float(ref.success_rate)

    def test_trial_pack_validation(self):
        with pytest.raises(ValueError):
            QBAConfig(n_parties=5, size_l=16, trial_pack=0)


class TestSingleLaunchPerRound:
    def test_one_pallas_call_per_round(self):
        # THE structural claim of the fusion: the fused engine's round
        # body contains ONE pallas_call where the tiled pair has two.
        # The round loop is a lax.scan, so each engine's whole-trial
        # jaxpr mentions pallas_call once per kernel in the body.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        key = jax.random.key(0)

        def n_calls(engine):
            ecfg = dataclasses.replace(
                cfg, round_engine=engine, tiled_block=16
            )
            jaxpr = jax.make_jaxpr(lambda k: run_trial(ecfg, k))(key)
            return str(jaxpr).count("pallas_call")

        assert n_calls("pallas_fused") == 1
        assert n_calls("pallas_tiled") == 2

    def test_demotion_to_tiled_warns(self, monkeypatch):
        # When the fused plan does not compile (probe demotion), the
        # forced engine falls back to the two-kernel tiled path with a
        # QBADemotionWarning — and the results are still correct.
        import qba_tpu.ops.round_kernel_tiled as rkt

        monkeypatch.setattr(
            rkt, "resolve_fused_block",
            lambda cfg, n_recv=None, trial_pack=1: None,
        )
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2,
            round_engine="pallas_fused", tiled_block=16,
        )
        keys = jax.random.split(jax.random.key(1), 4)
        with pytest.warns(QBADemotionWarning, match="demoting to the two-kernel"):
            demoted = jax.vmap(lambda k: run_trial(cfg, k))(keys)
        xla_cfg = dataclasses.replace(cfg, round_engine="xla")
        oracle = jax.vmap(lambda k: run_trial(xla_cfg, k))(keys)
        assert_equal(oracle, demoted)


class TestResolveMemoization:
    def test_same_shape_resolves_are_cached(self):
        # Satellite: repeated same-shape resolutions must hit the
        # in-process memo, not re-run the probe/planning logic.
        import qba_tpu.ops.round_kernel_tiled as rkt

        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        rkt.clear_resolve_caches()
        base = dict(rkt.PROBE_STATS)
        rkt.resolve_verdict_variant(cfg)
        rkt.resolve_tiled_block(cfg)
        rkt.resolve_rebuild_block(cfg)
        rkt.resolve_fused_block(cfg)
        rkt.resolve_trial_pack(cfg)
        misses_after_first = rkt.PROBE_STATS["resolve_misses"]
        assert misses_after_first >= base["resolve_misses"] + 5
        probes_after_first = rkt.PROBE_STATS["compile_probes"]
        rkt.resolve_verdict_variant(cfg)
        rkt.resolve_tiled_block(cfg)
        rkt.resolve_rebuild_block(cfg)
        rkt.resolve_fused_block(cfg)
        rkt.resolve_trial_pack(cfg)
        assert rkt.PROBE_STATS["resolve_misses"] == misses_after_first
        assert rkt.PROBE_STATS["resolve_hits"] >= base["resolve_hits"] + 5
        # No new compile probes on the second pass.
        assert rkt.PROBE_STATS["compile_probes"] == probes_after_first

    def test_measure_batch_skips_reprobe(self):
        # The benchmark harness calls the resolvers through run_trials
        # + engine attribution; a second same-shape measurement must
        # not re-resolve.
        import qba_tpu.ops.round_kernel_tiled as rkt
        from qba_tpu.benchmark import measure_batch

        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, trials=2,
            round_engine="pallas_fused", tiled_block=16,
        )
        rkt.clear_resolve_caches()
        measure_batch(cfg, reps=1, warmup=False)
        misses = rkt.PROBE_STATS["resolve_misses"]
        probes = rkt.PROBE_STATS["compile_probes"]
        measure_batch(cfg, reps=1, warmup=False)
        assert rkt.PROBE_STATS["resolve_misses"] == misses
        assert rkt.PROBE_STATS["compile_probes"] == probes

    def test_distinct_shapes_not_conflated(self):
        import qba_tpu.ops.round_kernel_tiled as rkt

        rkt.clear_resolve_caches()
        a = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        b = QBAConfig(n_parties=5, size_l=32, n_dishonest=2)
        blk_a = rkt.resolve_tiled_block(a)
        blk_b = rkt.resolve_tiled_block(b)
        # Both resolved independently (two misses, zero hits for the
        # second shape); explicit values are shape-legal.
        assert blk_a is None or (a.n_lieutenants * a.slots) % blk_a == 0
        assert blk_b is None or (b.n_lieutenants * b.slots) % blk_b == 0
        assert rkt.PROBE_STATS["resolve_misses"] >= 2


class TestSpmdFused:
    def test_spmd_accepts_fused_engine(self):
        # The party-sharded variant of the fused kernel: forced
        # pallas_fused under a dp x tp mesh must match the single-device
        # XLA engine trial for trial.  Needs >= 4 host devices (the CPU
        # test mesh is configured in conftest).
        from qba_tpu.backends.jax_backend import run_trials
        from qba_tpu.parallel import make_mesh
        from qba_tpu.parallel.spmd import run_trials_spmd

        n_devices = len(jax.devices())
        if n_devices < 4 or n_devices % 2:
            pytest.skip("needs an even device count >= 4")
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, trials=n_devices,
            round_engine="pallas_fused", tiled_block=16,
        )
        mesh = make_mesh({"dp": n_devices // 2, "tp": 2})
        spmd = run_trials_spmd(cfg, mesh)
        ref = run_trials(dataclasses.replace(cfg, round_engine="xla"))
        assert_equal(ref.trials, spmd.trials)
