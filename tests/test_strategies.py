"""Adversary strategy zoo (qba_tpu.adversary.model, ISSUE PR 9).

Four layers of contract:

* **Baseline pin** — ``strategy="reference"`` with zero noise is
  *bit-identical to historical outputs*: hardcoded golden success /
  decision vectors (computed on the pre-zoo code) must keep
  reproducing, on every round engine.  Any drift in the reference key
  tree — a new fold_in, a reordered draw — breaks these.
* **Distributional laws** — per-strategy chi-square tests of the
  sampled action/value laws at significance 1e-4 (the style of the
  reference-law tests in tests/test_adversary.py), at 5p and 11p.
* **Cross-engine / cross-backend bit-identity** — every strategy is
  expressed as the same effective-edit arrays from
  ``sample_attacks_round``, so the vectorized engines and the
  message-level local backend must agree trial for trial.
* **Loud validation** — unknown strategies, out-of-range noise
  probabilities, and forged values that could leave ``[0, w)`` raise
  ``ValueError`` instead of silently shifting verdicts.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats

from qba_tpu.adversary import (
    CLEAR_L_BIT,
    CLEAR_P_BIT,
    DROP_BIT,
    FORGE_BIT,
    FORGE_P_BIT,
    STRATEGIES,
    adversary_ctx,
    commander_orders,
    sample_attacks_round,
)
from qba_tpu.backends import run_trial_local
from qba_tpu.backends.jax_backend import run_trials, trial_keys
from qba_tpu.config import QBAConfig
from qba_tpu.rounds import run_trial

P = 1e-4  # chi-square significance shared by every law test


def _ctx_draws(cfg, key, round_idx=1, v_sent=None):
    """(attack, rand_v) under ``cfg.strategy`` with a per-key ctx."""
    if v_sent is None:
        v_sent = jnp.zeros((cfg.n_lieutenants,), jnp.int32)
    ctx = adversary_ctx(cfg, key, v_sent)
    att, rv, _ = sample_attacks_round(cfg, key, round_idx, ctx)
    return att, rv


# ---- baseline pin ------------------------------------------------------

# Golden outputs of the PRE-ZOO reference implementation (computed on
# the commit introducing the strategy field; the reference path adds no
# key-tree folds, so these must never move again).
GOLD_5P = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=6, seed=2026)
GOLD_5P_SUCCESS = [False, True, True, False, True, False]
GOLD_5P_DECISIONS = [
    [5, 0, 5, 0, 5], [6, 6, 6, 6, 6], [4, 4, 4, 4, 4],
    [7, 3, 2, 2, 2], [1, 0, 0, 0, 0], [4, 2, 4, 2, 2],
]
GOLD_11P = QBAConfig(n_parties=11, size_l=8, n_dishonest=3, trials=4, seed=77)
GOLD_11P_SUCCESS = [True, False, True, False]
GOLD_11P_DECISIONS = [
    [2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2],
    [7, 0, 0, 1, 1, 0, 0, 0, 0, 0, 0],
    [9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9],
    [6, 15, 15, 15, 15, 15, 2, 2, 2, 2, 2],
]


class TestReferenceBaselinePin:
    @pytest.mark.parametrize(
        "cfg, success, decisions",
        [
            (GOLD_5P, GOLD_5P_SUCCESS, GOLD_5P_DECISIONS),
            (GOLD_11P, GOLD_11P_SUCCESS, GOLD_11P_DECISIONS),
        ],
        ids=["5p", "11p"],
    )
    def test_reference_zero_noise_matches_golden(self, cfg, success, decisions):
        assert cfg.strategy == "reference"
        assert cfg.p_depolarize == 0.0 and cfg.p_measure_flip == 0.0
        mc = run_trials(cfg, trial_keys(cfg))
        assert [bool(x) for x in np.asarray(mc.trials.success)] == success
        assert np.asarray(mc.trials.decisions).tolist() == decisions

    @pytest.mark.slow
    def test_golden_holds_on_every_round_engine(self):
        keys = trial_keys(GOLD_5P)
        for engine in ("xla", "pallas", "pallas_tiled", "pallas_fused"):
            ecfg = dataclasses.replace(GOLD_5P, round_engine=engine)
            mc = jax.jit(jax.vmap(lambda k, c=ecfg: run_trial(c, k)))(keys)
            assert (
                [bool(x) for x in np.asarray(mc.success)] == GOLD_5P_SUCCESS
            ), engine
            assert (
                np.asarray(mc.decisions).tolist() == GOLD_5P_DECISIONS
            ), engine


# ---- distributional laws ----------------------------------------------


class TestColludeLaw:
    CFG = QBAConfig(n_parties=11, size_l=4, n_dishonest=3, strategy="collude")

    def test_one_shared_target_per_trial(self):
        for seed in range(8):
            att, rv = _ctx_draws(self.CFG, jax.random.key(seed))
            assert len(np.unique(np.asarray(rv))) == 1  # ONE value everywhere

    def test_target_uniform_over_reference_range(self):
        keys = jax.random.split(jax.random.key(0), 3000)
        v0 = jnp.zeros((self.CFG.n_lieutenants,), jnp.int32)
        targets = jax.vmap(
            lambda k: adversary_ctx(self.CFG, k, v0).collude_target
        )(keys)
        obs = np.bincount(np.asarray(targets), minlength=self.CFG.n_parties + 1)
        assert stats.chisquare(obs).pvalue > P

    def test_action_stream_bit_identical_to_reference(self):
        # Collusion only redirects the forged VALUE; the action bitmask
        # must stay byte-for-byte the reference law (same _ATTACK_TAG
        # stream), so flipping a study to collude perturbs nothing else.
        ref = dataclasses.replace(self.CFG, strategy="reference")
        for seed in range(4):
            k = jax.random.key(seed)
            att_c, _ = _ctx_draws(self.CFG, k)
            att_r, _, _ = sample_attacks_round(ref, k)
            np.testing.assert_array_equal(np.asarray(att_c), np.asarray(att_r))


class TestAdaptiveLaw:
    CFG = QBAConfig(n_parties=5, size_l=4, n_dishonest=2, strategy="adaptive")

    def _bits(self, round_idx, n_keys=64):
        v_sent = jnp.arange(self.CFG.n_lieutenants, dtype=jnp.int32) % self.CFG.w
        keys = jax.random.split(jax.random.key(round_idx), n_keys)
        att, rv = jax.vmap(
            lambda k: _ctx_draws(self.CFG, k, round_idx, v_sent)
        )(keys)
        return np.asarray(att).ravel(), np.asarray(rv), v_sent

    def test_early_rounds_drop_heavy(self):
        # 2 * round <= n_rounds: drop 1/2, the other four outcomes 1/8.
        assert 2 * 1 <= self.CFG.n_rounds
        bits, _, _ = self._bits(round_idx=1)
        obs = np.array([
            (bits == b).sum()
            for b in (0, DROP_BIT, FORGE_BIT, CLEAR_P_BIT, CLEAR_L_BIT)
        ])
        assert obs.sum() == bits.size
        exp = bits.size * np.array([1 / 8, 1 / 2, 1 / 8, 1 / 8, 1 / 8])
        assert stats.chisquare(obs, exp).pvalue > P

    def test_late_rounds_forge_heavy(self):
        last = self.CFG.n_rounds
        assert 2 * last > self.CFG.n_rounds
        bits, _, _ = self._bits(round_idx=last)
        obs = np.array([
            (bits == b).sum()
            for b in (0, DROP_BIT, FORGE_BIT, CLEAR_P_BIT, CLEAR_L_BIT)
        ])
        exp = bits.size * np.array([1 / 8, 1 / 8, 1 / 2, 1 / 8, 1 / 8])
        assert stats.chisquare(obs, exp).pvalue > P

    def test_forged_value_never_received_value_and_in_domain(self):
        w = self.CFG.w
        _, rv, v_sent = self._bits(round_idx=self.CFG.n_rounds)
        senders = np.arange(rv.shape[1]) // self.CFG.slots
        v_recv = np.asarray(v_sent)[senders][None, :, None]
        assert ((rv >= 0) & (rv < w)).all()
        assert (rv != v_recv).all()
        # offset = (rand_v - v_recv) mod w uniform over [1, w).
        offs = ((rv - v_recv) % w).ravel()
        obs = np.bincount(offs, minlength=w)
        assert obs[0] == 0
        assert stats.chisquare(obs[1:]).pvalue > P


class TestSplitLaw:
    CFG = QBAConfig(n_parties=5, size_l=4, n_dishonest=2, strategy="split")

    def test_effective_bit_multinomial(self):
        # action 0 -> FORGE_P (1/4); 1 -> FORGE_P+FORGE (1/4);
        # 2 -> CLEAR_L (1/4); 3 -> drop w.p. 1/2 (1/8 drop, 1/8 clean).
        keys = jax.random.split(jax.random.key(3), 64)
        att = np.concatenate([
            np.asarray(sample_attacks_round(self.CFG, k)[0]).ravel()
            for k in keys
        ])
        support = (FORGE_P_BIT, FORGE_P_BIT | FORGE_BIT, CLEAR_L_BIT,
                   DROP_BIT, 0)
        obs = np.array([(att == b).sum() for b in support])
        assert obs.sum() == att.size  # nothing outside the split support
        exp = att.size * np.array([1 / 4, 1 / 4, 1 / 4, 1 / 8, 1 / 8])
        assert stats.chisquare(obs, exp).pvalue > P

    def test_p_is_inflated_never_cleared(self):
        for seed in range(6):
            att, _, _ = sample_attacks_round(self.CFG, jax.random.key(seed))
            assert not bool(jnp.any(att & CLEAR_P_BIT))

    def test_commander_equivocates_by_rank_parity(self):
        cfg = QBAConfig(n_parties=11, size_l=4, strategy="split")
        for seed in range(12):
            v_sent, _ = commander_orders(
                cfg, jax.random.key(seed), jnp.asarray(False)
            )
            vs = np.asarray(v_sent)  # lieutenants at ranks 2..n_parties
            even, odd = vs[0::2], vs[1::2]  # rank parity partition
            assert len(set(even)) == 1 and len(set(odd)) == 1
            assert even[0] != odd[0]

    def test_honest_commander_unaffected_by_strategy(self):
        cfg = QBAConfig(n_parties=11, size_l=4, strategy="split")
        ref = dataclasses.replace(cfg, strategy="reference")
        for seed in range(4):
            k = jax.random.key(seed)
            vs_s, v_s = commander_orders(cfg, k, jnp.asarray(True))
            vs_r, v_r = commander_orders(ref, k, jnp.asarray(True))
            np.testing.assert_array_equal(np.asarray(vs_s), np.asarray(vs_r))
            assert int(v_s) == int(v_r)


# ---- cross-engine / cross-backend bit-identity -------------------------

ZOO_CONFIGS = [
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=8, seed=21,
              strategy="collude"),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=8, seed=22,
              strategy="adaptive"),
    QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=8, seed=23,
              strategy="split"),
]


@pytest.mark.parametrize("cfg", ZOO_CONFIGS, ids=lambda c: c.strategy)
def test_local_backend_agrees_per_trial(cfg):
    # Message-level local backend vs vectorized jax engine: the same
    # differential as tests/test_differential.py, per strategy.
    keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
    mc = run_trials(cfg, keys)
    for t in range(cfg.trials):
        local = run_trial_local(cfg, keys[t])
        assert mc.trials.decisions[t].tolist() == local["decisions"], t
        assert bool(mc.trials.success[t]) == local["success"], t


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["collude", "adaptive", "split"])
def test_round_engines_bit_identical_per_strategy(strategy):
    cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2, trials=4,
                    seed=31, strategy=strategy)
    keys = jax.random.split(jax.random.key(cfg.seed), cfg.trials)
    outs = []
    for engine in ("xla", "pallas", "pallas_tiled", "pallas_fused"):
        ecfg = dataclasses.replace(cfg, round_engine=engine)
        outs.append(jax.jit(jax.vmap(lambda k, c=ecfg: run_trial(c, k)))(keys))
    base = outs[0]
    for got in outs[1:]:
        assert base.vi.tolist() == got.vi.tolist(), strategy
        assert base.decisions.tolist() == got.decisions.tolist(), strategy
        assert base.success.tolist() == got.success.tolist(), strategy
        assert base.overflow.tolist() == got.overflow.tolist(), strategy


def test_strategies_change_protocol_outcomes():
    # Sanity on the POINT of the zoo: each non-reference strategy must
    # actually shift per-trial outcomes for the same trial keys (the
    # zoo is not a relabeling of the reference law).
    cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=2, trials=64, seed=5)
    ref = run_trials(cfg, trial_keys(cfg))
    for strategy in ("collude", "adaptive", "split"):
        got = run_trials(
            dataclasses.replace(cfg, strategy=strategy), trial_keys(cfg)
        )
        assert (
            got.trials.decisions.tolist() != ref.trials.decisions.tolist()
        ), strategy


# ---- loud validation ---------------------------------------------------


class TestValidation:
    def test_unknown_strategy_rejected(self):
        with pytest.raises(ValueError, match="unknown strategy"):
            QBAConfig(n_parties=5, size_l=8, n_dishonest=1, strategy="chaos")

    @pytest.mark.parametrize("field", ["p_depolarize", "p_measure_flip"])
    @pytest.mark.parametrize("value", [-0.1, 1.5])
    def test_noise_probability_range_rejected(self, field, value):
        with pytest.raises(ValueError, match=field):
            QBAConfig(n_parties=5, size_l=8, n_dishonest=1, **{field: value})

    def test_broadcast_scope_restricted_to_reference(self):
        with pytest.raises(ValueError, match="broadcast"):
            QBAConfig(n_parties=5, size_l=8, n_dishonest=1,
                      strategy="collude", attack_scope="broadcast")

    def test_stateful_strategies_demand_their_inputs(self):
        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=1,
                        strategy="collude")
        with pytest.raises(ValueError, match="ctx"):
            sample_attacks_round(cfg, jax.random.key(0))
        cfg = dataclasses.replace(cfg, strategy="adaptive")
        with pytest.raises(ValueError, match="round_idx"):
            sample_attacks_round(cfg, jax.random.key(0))

    def test_forge_bound_outside_value_domain_rejected(self, monkeypatch):
        # No built-in strategy can trip this (w >= n_parties + 1 by
        # construction) — the guard exists for future strategies, so
        # widen a bound artificially and demand the loud failure.
        from qba_tpu.adversary import model

        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=1)
        monkeypatch.setitem(
            model.STRATEGY_FORGE_BOUND, "reference", lambda c: c.w + 1
        )
        with pytest.raises(ValueError, match="outside the value domain"):
            sample_attacks_round(cfg, jax.random.key(0))

    def test_strategy_tuple_is_the_config_contract(self):
        assert set(STRATEGIES) == {"reference", "collude", "adaptive", "split"}
        for s in STRATEGIES:
            QBAConfig(n_parties=5, size_l=8, n_dishonest=1, strategy=s)
