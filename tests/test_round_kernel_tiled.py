"""Packet-tiled round engine vs the XLA round engine.

The tiled engine (:mod:`qba_tpu.ops.round_kernel_tiled` — blocked Pallas
verdict kernel + Pallas rebuild kernel over a compacted packet pool)
must produce bit-identical verdicts to the XLA path for the same trial
keys: compaction preserves the (sender, slot) packet processing order
(docs/DIVERGENCES.md D5) and each pool entry keeps its mailbox cell id,
so the per-cell attack draws retain their identity.  Runs in interpreter
mode on the CPU test mesh; the same kernels compile for real on TPU
(``round_engine="auto"`` picks them for configs the monolithic kernel
cannot compile — 33-party lossless, the reference's sizeL=1000).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from qba_tpu.config import QBAConfig
from qba_tpu.diagnostics import QBAProbeWarning
from qba_tpu.rounds import run_trial


def both(cfg, seed, n, blk):
    keys = jax.random.split(jax.random.key(seed), n)
    xla_cfg = dataclasses.replace(cfg, round_engine="xla")
    til_cfg = dataclasses.replace(
        cfg, round_engine="pallas_tiled", tiled_block=blk
    )
    a = jax.jit(jax.vmap(lambda k: run_trial(xla_cfg, k)))(keys)
    b = jax.jit(jax.vmap(lambda k: run_trial(til_cfg, k)))(keys)
    return a, b


def assert_equal(a, b):
    assert a.vi.tolist() == b.vi.tolist()
    assert a.decisions.tolist() == b.decisions.tolist()
    assert a.success.tolist() == b.success.tolist()
    assert a.overflow.tolist() == b.overflow.tolist()


class TestTiledEquivalence:
    def test_all_honest_multiblock(self):
        # n_pool = 4 * 8 = 32; blk=8 -> 4 grid blocks.
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=0)
        assert_equal(*both(cfg, 0, 4, 8))

    def test_adversarial_multiblock(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        a, b = both(cfg, 1, 8, 8)
        assert_equal(a, b)
        assert not bool(jnp.all(a.honest))

    def test_single_block_whole_pool(self):
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        assert_equal(*both(cfg, 1, 8, 32))

    def test_wide_positions_single_receiver_group(self):
        cfg = QBAConfig(n_parties=4, size_l=128, n_dishonest=1)
        assert_equal(*both(cfg, 5, 4, 8))

    def test_tail_overlap_group(self):
        # n_lieutenants odd with lane-group 2: tail group re-covers.
        cfg = QBAConfig(n_parties=6, size_l=48, n_dishonest=2)
        assert_equal(*both(cfg, 6, 6, 8))

    def test_racy_delivery(self):
        cfg = QBAConfig(
            n_parties=4, size_l=8, n_dishonest=1, delivery="racy",
            p_late=0.5,
        )
        assert_equal(*both(cfg, 2, 8, 8))

    def test_tight_slot_bound_overflow(self):
        # slots=1 -> overflow flag must match the XLA engine's exactly.
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_accepts_per_round=1
        )
        assert_equal(*both(cfg, 3, 16, 4))

    def test_broadcast_attack_scope(self):
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2,
            attack_scope="broadcast",
        )
        assert_equal(*both(cfg, 7, 8, 8))

    def test_two_presence_planes(self):
        # w = 64 needs two 32-bit value-presence planes (the 33-party
        # north-star class, scaled down in sizeL/trials for CI).
        cfg = QBAConfig(n_parties=33, size_l=8, n_dishonest=2)
        assert cfg.w == 64
        assert_equal(*both(cfg, 8, 2, 64))

    def test_group_variant_bit_identical(self, monkeypatch):
        # Off-TPU the resolver picks the all-receiver variant whenever
        # the exactness gate holds — pin the group variant (lane-group
        # flag algebra + the round-6 parallel first-accept reduction)
        # against the XLA engine too (it is the TPU fallback and the
        # party-sharded engine's only family).
        import qba_tpu.ops.round_kernel_tiled as rkt

        monkeypatch.setattr(
            rkt, "resolve_verdict_variant",
            lambda cfg, n_recv=None: "group",
        )
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        assert_equal(*both(cfg, 1, 8, 8))
        cfg_w = QBAConfig(n_parties=33, size_l=8, n_dishonest=2)
        assert_equal(*both(cfg_w, 8, 2, 64))

    def test_parallel_accept_outside_group_window(self, monkeypatch):
        # The round-6 parallel accept must cover the configs the
        # round-4 group-batched dedup excluded: grp == 1 (sizeL >= 128
        # — one receiver already fills a lane tile) and grp * w > 512
        # (the 33p/sizeL=8 shape: grp=16, w=64 -> 1024 lanes).  Both
        # previously fell to the serial per-receiver chain; force the
        # group variant and pin bit-identity to the XLA engine.
        import qba_tpu.ops.round_kernel_tiled as rkt
        from qba_tpu.ops.round_kernel import _lane_group

        monkeypatch.setattr(
            rkt, "resolve_verdict_variant",
            lambda cfg, n_recv=None: "group",
        )
        cfg_grp1 = QBAConfig(n_parties=4, size_l=128, n_dishonest=1)
        assert _lane_group(cfg_grp1.size_l, cfg_grp1.n_lieutenants) == 1
        assert_equal(*both(cfg_grp1, 5, 4, 8))
        cfg_wide = QBAConfig(n_parties=33, size_l=8, n_dishonest=2)
        grp = _lane_group(cfg_wide.size_l, cfg_wide.n_lieutenants)
        assert grp * cfg_wide.w > 512
        assert_equal(*both(cfg_wide, 8, 2, 64))

    def test_serial_accept_variant_bit_identical(self, monkeypatch):
        # "group-serial" (the pre-round-6 accept chain) stays reachable
        # as the TPU compile-demotion fallback — pin it against the XLA
        # engine at the same configs the parallel path covers, so the
        # three accept formulations are mutually bit-identical.
        import qba_tpu.ops.round_kernel_tiled as rkt

        monkeypatch.setattr(
            rkt, "resolve_verdict_variant",
            lambda cfg, n_recv=None: "group-serial",
        )
        assert_equal(
            *both(QBAConfig(n_parties=5, size_l=16, n_dishonest=2), 1, 8, 8)
        )
        assert_equal(
            *both(QBAConfig(n_parties=4, size_l=128, n_dishonest=1), 5, 4, 8)
        )
        assert_equal(
            *both(QBAConfig(n_parties=33, size_l=8, n_dishonest=2), 8, 2, 64)
        )

    def test_variant_static_gate(self):
        from qba_tpu.ops.verdict_algebra import all_receiver_supported

        assert all_receiver_supported(64, 64)  # north star
        assert all_receiver_supported(1000, 16)  # reference scale
        assert not all_receiver_supported(64, 128)  # > 2 bit planes
        assert not all_receiver_supported(2**12, 64)  # f32 identity


class TestXlaRebuildFallback:
    def test_rebuild_pool_bit_identical(self, monkeypatch):
        # On TPU the XLA rebuild_pool takes over whenever the rebuild
        # kernel's probe fails; force that path here (the CPU resolver
        # otherwise always picks the kernel) and pin bit-identity.
        import qba_tpu.ops.round_kernel_tiled as rkt

        monkeypatch.setattr(rkt, "resolve_rebuild_block", lambda cfg: None)
        cfg = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        assert_equal(*both(cfg, 1, 8, 8))

    def test_spmd_accepts_tiled_engine(self):
        # Round 4: the tiled engine HAS a party-sharded variant now —
        # an explicit pallas_tiled request runs it (bit-equivalence is
        # pinned in tests/test_parallel.py::TestPartyShardedTiled).
        from qba_tpu.parallel.mesh import make_mesh
        from qba_tpu.parallel.spmd import run_trials_spmd

        cfg = QBAConfig(
            n_parties=5, size_l=8, trials=2, round_engine="pallas_tiled"
        )
        mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
        out = run_trials_spmd(cfg, mesh)
        assert out.trials.success.shape == (2,)


class TestProbeTransientHandling:
    """Probe failures born from transient tunnel/helper errors must
    retry once and never be cached — a cached false 'does not compile'
    verdict pins the config to a slower engine (observed on hardware:
    it dropped the north star to the XLA engine, round 4)."""

    @pytest.fixture(autouse=True)
    def _isolate_probe_disk(self, monkeypatch):
        # On a TPU backend the machine-wide disk cache is live: a stale
        # 'test-kernel' entry would short-circuit _probe_plan before
        # compile_one runs, and these probes must never pollute the
        # real probe JSON.  Stub both ends; record puts for assertions.
        import qba_tpu.ops.round_kernel_tiled as rkt

        self.puts = []
        monkeypatch.setattr(rkt, "_probe_disk_get", lambda k: None)
        monkeypatch.setattr(
            rkt, "_probe_disk_put", lambda k, v: self.puts.append((k, v))
        )

    def _plan(self, cfg, compile_one):
        from qba_tpu.ops.round_kernel_tiled import _probe_plan

        cache: dict = {}
        return (
            _probe_plan(
                "test-kernel", cfg, [16, 8], compile_one, cache,
                "falling back", extra="unit",
            ),
            cache,
        )

    def test_transient_failure_retries_and_is_not_cached(self):
        cfg = QBAConfig(n_parties=5, size_l=8)
        calls = []

        def flaky(blk):
            calls.append(blk)
            if len(calls) == 1:
                raise RuntimeError(
                    "INTERNAL: remote_compile: HTTP 500: subprocess exit"
                )

        chosen, cache = self._plan(cfg, flaky)
        # First candidate failed transiently once, retried, succeeded.
        assert chosen == 16
        assert calls == [16, 16]
        assert cache  # successful verdicts DO cache

    def test_persistent_transient_failure_not_cached(self):
        cfg = QBAConfig(n_parties=5, size_l=8)

        def always_transient(blk):
            raise RuntimeError("remote_compile: HTTP 500")

        with pytest.warns(QBAProbeWarning, match="compile probe failed"):
            chosen, cache = self._plan(cfg, always_transient)
        assert chosen is None
        assert not cache  # a flaky tunnel must not pin the verdict

    def test_deterministic_failure_is_cached(self):
        cfg = QBAConfig(n_parties=5, size_l=8)
        calls = []

        def vmem_oom(blk):
            calls.append(blk)
            raise RuntimeError("Mosaic: scoped vmem limit exceeded")

        with pytest.warns(QBAProbeWarning, match="compile probe failed"):
            chosen, cache = self._plan(cfg, vmem_oom)
        assert chosen is None
        assert calls == [16, 8]  # no retry per candidate; all tried
        assert cache  # real shape verdicts persist

    def test_transient_on_preferred_skips_disk_write(self):
        # ADVICE r4: a transient tunnel error on the preferred candidate
        # followed by a clean compile of a slower one must not pin the
        # slower block machine-wide — the in-process cache may keep it
        # (this process already paid the probes), but the disk cache
        # must stay empty so the next process re-probes the preferred.
        cfg = QBAConfig(n_parties=5, size_l=8)

        def flaky_preferred(blk):
            if blk == 16:
                raise RuntimeError("remote_compile: HTTP 500")

        chosen, cache = self._plan(cfg, flaky_preferred)
        assert chosen == 8  # the slower candidate won this process
        assert cache  # in-process verdict kept
        assert self.puts == []  # but never persisted to disk

    def test_deterministic_preferred_failure_still_persists(self):
        # Control for the above: a *deterministic* preferred-candidate
        # failure is a real shape verdict — the slower choice persists.
        import qba_tpu.ops.round_kernel_tiled as rkt

        cfg = QBAConfig(n_parties=5, size_l=8)

        def oom_preferred(blk):
            if blk == 16:
                raise RuntimeError("Mosaic: scoped vmem limit exceeded")

        chosen, cache = self._plan(cfg, oom_preferred)
        assert chosen == 8
        assert self.puts == [(rkt._probe_disk_key("test-kernel", cfg,
                                                  extra="unit"), 8)]

    def test_transient_blip_on_winner_still_persists(self):
        # A deterministic preferred failure plus a transient blip that
        # the WINNING candidate recovered from within its own retry is
        # a fully real verdict — it must persist (the skip keys on
        # abandoned-on-transient candidates, not on any transient seen).
        import qba_tpu.ops.round_kernel_tiled as rkt

        cfg = QBAConfig(n_parties=5, size_l=8)
        calls = []

        def mixed(blk):
            calls.append(blk)
            if blk == 16:
                raise RuntimeError("Mosaic: scoped vmem limit exceeded")
            if calls.count(8) == 1:
                raise RuntimeError("remote_compile: HTTP 500")

        chosen, cache = self._plan(cfg, mixed)
        assert chosen == 8
        assert calls == [16, 8, 8]
        assert self.puts == [(rkt._probe_disk_key("test-kernel", cfg,
                                                  extra="unit"), 8)]


class TestPoolMechanics:
    def test_tiled_block_validation(self):
        with pytest.raises(ValueError, match="tiled_block"):
            QBAConfig(n_parties=5, size_l=8, tiled_block=7)

    def test_block_candidates_divide_pool(self):
        from qba_tpu.ops.round_kernel_tiled import (
            block_candidates,
            rebuild_candidates,
        )

        cfg = QBAConfig(n_parties=33, size_l=64, n_dishonest=10)
        n_pool = cfg.n_lieutenants * cfg.slots
        for b in block_candidates(cfg) + rebuild_candidates(cfg):
            assert n_pool % b == 0

    def test_pool_compaction_preserves_order(self):
        # Sent entries must land at the front, in sender order, with
        # their mailbox cell ids.
        from qba_tpu.ops.round_kernel_tiled import pool_from_step3a
        from qba_tpu.rounds.engine import setup_trial, step3a_one

        cfg = QBAConfig(n_parties=5, size_l=8, n_dishonest=0)
        _, lieu_lists, p_rows, v_sent, _, _ = setup_trial(
            cfg, jax.random.key(0)
        )
        _, out_cells = jax.vmap(
            lambda p, v, li: step3a_one(cfg, p, v, li)
        )(p_rows, v_sent, lieu_lists)
        from qba_tpu.ops.round_kernel_tiled import META_CELL, META_SENT

        pool = pool_from_step3a(cfg, out_cells)
        sent = pool[3][:, META_SENT]
        n_sent = int(jnp.sum(sent))
        # compacted: all sent entries first
        assert sent.tolist() == [1] * n_sent + [0] * (len(sent) - n_sent)
        # cell ids strictly increasing over the sent prefix (sender order)
        cells = pool[3][:n_sent, META_CELL].tolist()
        assert cells == sorted(cells)

    def test_vals_dtype_bf16_exact_range(self):
        from qba_tpu.ops.round_kernel_tiled import pool_vals_dtype

        assert pool_vals_dtype(
            QBAConfig(n_parties=33, size_l=8)
        ) == jnp.bfloat16
        # w > 256 would lose integer exactness in bf16 -> int32.
        big = QBAConfig(n_parties=300, size_l=8)
        assert big.w == 512
        assert pool_vals_dtype(big) == jnp.int32


class TestMaxEvidenceRowsInvariant:
    """The append_own fullness guard (consistent_after_append) and the
    config invariant that keeps it unreachable (VERDICT r2 item 7)."""

    def test_too_small_bound_rejected(self):
        # max_l < n_rounds + 1 would drop evidence rows mid-protocol
        # and silently split the batched engines from the spec.
        with pytest.raises(ValueError, match="max_evidence_rows"):
            QBAConfig(
                n_parties=5, size_l=8, n_dishonest=2, max_evidence_rows=3
            )

    def test_enlarged_bound_keeps_engines_identical(self):
        # Decoupling max_l upward exercises the appended guard path in
        # all engines; verdicts must stay bit-identical.
        cfg = QBAConfig(
            n_parties=5, size_l=16, n_dishonest=2, max_evidence_rows=6
        )
        assert cfg.max_l == 6
        a, b = both(cfg, 9, 8, 8)
        assert_equal(a, b)
        pal_cfg = dataclasses.replace(cfg, round_engine="pallas")
        keys = jax.random.split(jax.random.key(9), 8)
        c = jax.jit(jax.vmap(lambda k: run_trial(pal_cfg, k)))(keys)
        assert_equal(a, c)

    def test_enlarged_bound_matches_default_decisions(self):
        # A larger evidence capacity must not change protocol outcomes
        # (the bound is provably never reached).
        base = QBAConfig(n_parties=5, size_l=16, n_dishonest=2)
        wide = dataclasses.replace(base, max_evidence_rows=7)
        keys = jax.random.split(jax.random.key(4), 8)
        a = jax.jit(jax.vmap(lambda k: run_trial(base, k)))(keys)
        b = jax.jit(jax.vmap(lambda k: run_trial(wide, k)))(keys)
        assert a.decisions.tolist() == b.decisions.tolist()
        assert a.success.tolist() == b.success.tolist()


class TestRooflineModel:
    def test_model_shape_and_scaling(self):
        from qba_tpu.ops.round_kernel_tiled import pool_bytes, roofline_model

        cfg = QBAConfig(n_parties=33, size_l=64, n_dishonest=10)
        m1 = roofline_model(cfg, 1)
        m1000 = roofline_model(cfg, 1000)
        assert m1["per_round_per_trial_bytes"] > 0
        assert 0 < m1["pool_share"] < 1
        # Batch bound scales linearly in trials and covers the pool term.
        assert m1000["batch_bytes_upper_bound"] == (
            1000 * m1["batch_bytes_upper_bound"]
        )
        pool = pool_bytes(cfg, 1000)
        assert m1000["batch_bytes_upper_bound"] > (
            3 * pool["padded_bytes"] * cfg.n_rounds
        )


class TestMatmulPrecisionExactness:
    """Round 5: the wrong-draw bug.  An f32-dtype dot at DEFAULT matmul
    precision may lower through single-pass bf16 (backend- and
    lowering-dependent — the same program was exact at batch 1 and lossy
    at batch 16), rounding integer operands > 256 to even.  The rebuild
    kernel's meta gather carries cell ids up to n_pool-1 = 2047, so at
    33-party scale sources at odd cells > 256 were rebuilt with a
    NEIGHBOR cell's corruption draws — silently corrupting north-star
    trials while every small-config test stayed green.  Fix: _prec /
    _exact_prec (Precision.HIGHEST on every integer dot whose operands
    can exceed bf16's exact range).
    """

    def test_rebuild_kernel_high_cells_matches_xla_rebuild(self):
        # Direct contract test at high occupancy: a synthetic compacted
        # pool whose packets sit at odd cell ids > 256, every receiver
        # accepting many packets — the regime the protocol-level suites
        # never reached.  Kernel and XLA rebuild must agree bit-for-bit.
        import numpy as np

        from qba_tpu.ops.round_kernel_tiled import (
            META_CELL,
            build_rebuild_kernel,
            rebuild_pool,
            resolve_rebuild_block,
        )

        cfg = QBAConfig(n_parties=33, size_l=8, n_dishonest=10)
        n_rv, slots, max_l, s = (
            cfg.n_lieutenants, cfg.slots, cfg.max_l, cfg.size_l,
        )
        n_pool = n_rv * slots
        rng = np.random.default_rng(3)
        n_sent = 700  # fills cells far past 256
        cells = np.sort(
            rng.choice(n_pool, size=n_sent, replace=False)
        ).astype(np.int32)
        vals = np.full((max_l, n_pool, s), -1, np.int32)
        lens = np.zeros((n_pool, max_l), np.int32)
        meta = np.zeros((n_pool, 4), np.int32)
        cnt = rng.integers(1, 3, size=n_sent).astype(np.int32)
        for i in range(n_sent):
            vals[: cnt[i], i] = rng.integers(0, cfg.w, size=(cnt[i], s))
            lens[i, : cnt[i]] = s
        meta[:n_sent, 0] = cnt
        meta[:n_sent, 1] = rng.integers(0, cfg.w, size=n_sent)
        meta[:n_sent, 2] = 1
        meta[:n_sent, META_CELL] = cells
        p = rng.integers(0, 2, size=(n_pool, s)).astype(np.int32)
        li = rng.integers(0, cfg.w, size=(n_rv, s)).astype(np.int32)
        acc = np.zeros((n_pool, n_rv), np.int32)
        acc[:n_sent] = rng.random((n_sent, n_rv)) < 0.5  # heavy accepts
        attack = rng.integers(0, 16, size=(n_pool, n_rv)).astype(np.int32)
        rand_v = rng.integers(0, cfg.n_parties + 1,
                              size=(n_pool, n_rv)).astype(np.int32)
        honest = rng.integers(0, 2, size=(n_pool, 1)).astype(np.int32)

        from qba_tpu.ops.round_kernel_tiled import pool_vals_dtype

        vdt = pool_vals_dtype(cfg)
        pool = (
            jnp.asarray(vals, vdt), jnp.asarray(lens),
            jnp.asarray(p, vdt), jnp.asarray(meta),
        )
        r_idx = jnp.asarray(2)
        blk_d = resolve_rebuild_block(cfg)
        rebuild_k = build_rebuild_kernel(cfg, blk_d, interpret=True)
        out_k, ovf_k = rebuild_k(
            r_idx, *pool, jnp.asarray(li), jnp.asarray(acc),
            jnp.asarray(attack), jnp.asarray(rand_v), jnp.asarray(honest),
        )
        cell = pool[3][:, META_CELL]
        out_x, ovf_x = rebuild_pool(
            cfg, r_idx, pool, jnp.asarray(li), jnp.asarray(acc),
            jnp.take(jnp.asarray(attack), cell, axis=0),
            jnp.take(jnp.asarray(rand_v), cell, axis=0),
            jnp.take(jnp.asarray(honest), cell, axis=0),
        )
        import numpy as _np

        for a_, b_ in zip(out_k, out_x):
            assert (_np.asarray(a_) == _np.asarray(b_)).all()
        assert bool(ovf_k) == bool(ovf_x)

    @pytest.mark.slow
    def test_north_star_batch_bit_identical(self):
        # The end-to-end repro that exposed the bug: the exact 16
        # vmapped trials (backend key tree, seed 5) at the 33-party
        # north-star shape, tiled vs XLA engine.  Trials 9/11/12
        # diverged before the fix (and which trials diverged depended
        # on the batch composition).  Marked slow (~minutes on CPU —
        # the tier-1 run filters `-m 'not slow'`; run explicitly via
        # `pytest -m slow` or without the filter) but guards the
        # flagship engine's headline configuration.
        import dataclasses as _dc

        import numpy as _np

        from qba_tpu.backends.jax_backend import (
            fence, run_trials, trial_keys,
        )

        cfg = QBAConfig(
            n_parties=33, size_l=64, n_dishonest=10, trials=16, seed=5,
            round_engine="pallas_tiled", tiled_block=128,
        )
        keys = trial_keys(cfg)
        r_t = run_trials(cfg, keys)
        fence(r_t)
        cfg_x = _dc.replace(cfg, round_engine="xla", tiled_block=None)
        r_x = run_trials(cfg_x, keys)
        fence(r_x)
        assert (
            _np.asarray(r_t.trials.decisions)
            == _np.asarray(r_x.trials.decisions)
        ).all()
        assert (
            _np.asarray(r_t.trials.vi) == _np.asarray(r_x.trials.vi)
        ).all()
