"""Adversary model unit tests (``tfg.py:101-125,169-181,271-284``)."""

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.adversary import (
    assign_dishonest,
    commander_orders,
    corrupt_at_delivery,
    sample_attacks_round,
)


from qba_tpu.config import QBAConfig
from qba_tpu.core import append_own
from qba_tpu.core.types import Packet, empty_evidence


def draws_for(cfg, key):
    """One cell's (attack, rand_v) from the batched round draws."""
    att, rv, _ = sample_attacks_round(cfg, key)
    return att[0, 0], rv[0, 0]


class TestAssignDishonest:
    def test_counts_and_rank0_honest(self):
        cfg = QBAConfig(n_parties=11, size_l=4, n_dishonest=5)
        keys = jax.random.split(jax.random.key(0), 50)
        masks = jax.vmap(lambda k: assign_dishonest(cfg, k))(keys)
        assert masks.shape == (50, 12)
        assert bool(jnp.all(masks[:, 0]))  # QSD never dishonest
        np.testing.assert_array_equal(
            np.asarray(jnp.sum(~masks, axis=1)), np.full(50, 5)
        )

    def test_commander_can_be_dishonest(self):
        # tfg.py:105 draws from 1..nParties inclusive of the commander
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        keys = jax.random.split(jax.random.key(1), 200)
        masks = jax.vmap(lambda k: assign_dishonest(cfg, k))(keys)
        frac_comm_dishonest = float(jnp.mean(~masks[:, 1]))
        assert 0.15 < frac_comm_dishonest < 0.55  # ~1/3

    def test_zero_dishonest(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=0)
        assert bool(jnp.all(assign_dishonest(cfg, jax.random.key(2))))


class TestCommanderOrders:
    def test_honest_sends_same_v(self):
        cfg = QBAConfig(n_parties=11, size_l=4)
        v_sent, v = commander_orders(cfg, jax.random.key(0), jnp.asarray(True))
        assert bool(jnp.all(v_sent == v))
        assert 0 <= int(v) < cfg.w

    def test_dishonest_equivocates_at_split(self):
        cfg = QBAConfig(n_parties=11, size_l=4)
        found_split = False
        for i in range(20):
            v_sent, _ = commander_orders(
                cfg, jax.random.key(i), jnp.asarray(False)
            )
            vs = np.asarray(v_sent)
            # ranks 2..6 get v1, ranks 7..11 get v2, v1 != v2 (tfg.py:176-181)
            assert len(set(vs[:5])) == 1 and len(set(vs[5:])) == 1
            assert vs[0] != vs[5]
            found_split = True
        assert found_split

    def test_v2_uniform_over_not_v1(self):
        # The reference's rejection loop (tfg.py:173-175) makes
        # v2 | v1 uniform over the w-1 values != v1; chi-square per v1
        # at significance 1e-4, and v1 itself uniform over [0, w)
        # (VERDICT r1 #7 statistical hardening).
        from scipy import stats

        cfg = QBAConfig(n_parties=3, size_l=4)  # w = 4
        vs = []
        for i in range(1200):
            v_sent, _ = commander_orders(cfg, jax.random.key(i), jnp.asarray(False))
            vs.append((int(v_sent[0]), int(v_sent[-1])))
        v1s = np.array([v1 for v1, _ in vs])
        assert stats.chisquare(np.bincount(v1s, minlength=4)).pvalue > 1e-4
        v2_given_v1 = {}
        for v1, v2 in vs:
            assert v1 != v2
            v2_given_v1.setdefault(v1, []).append(v2)
        assert set(v2_given_v1) == set(range(4))
        for v1, v2s in v2_given_v1.items():
            counts = np.bincount(v2s, minlength=4)
            assert counts[v1] == 0
            others = counts[[i for i in range(4) if i != v1]]
            assert stats.chisquare(others).pvalue > 1e-4, (v1, counts)


class TestCorruptAtDelivery:
    def _packet(self, cfg):
        ev = append_own(
            empty_evidence(cfg.max_l, cfg.size_l),
            jnp.asarray([True, True, False, False]),
            jnp.asarray([2, 3, 0, 0], dtype=jnp.int32),
        )
        return Packet(
            p_mask=jnp.asarray([True, True, False, False]),
            v=jnp.asarray(1, jnp.int32),
            evidence=ev,
        )

    def test_honest_sender_untouched(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        for i in range(10):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(True)
            )
            assert bool(delivered)
            assert int(out.v) == 1
            assert out.p_mask.tolist() == pk.p_mask.tolist()
            assert int(out.evidence.count) == 1

    def test_dishonest_actions_all_occur(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        seen = {"drop": 0, "v": 0, "p": 0, "l": 0, "clean": 0}
        for i in range(400):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(False)
            )
            if not bool(delivered):
                seen["drop"] += 1
            elif int(out.v) != 1:
                seen["v"] += 1
            elif not bool(jnp.any(out.p_mask)):
                seen["p"] += 1
            elif int(out.evidence.count) == 0:
                seen["l"] += 1
            else:
                seen["clean"] += 1
        # actions are ~25% each; drop additionally flips a fair coin
        # (tfg.py:274), so ~12.5% of deliveries vanish; corrupt-v draws
        # from [0, nParties+1) and can coincide with the original v
        assert seen["drop"] > 25
        assert seen["v"] > 60
        assert seen["p"] > 60
        assert seen["l"] > 60

    def test_corrupt_v_range(self):
        # tfg.py:277: random order from [0, nParties+1), NOT [0, w)
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        vs = set()
        for i in range(600):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(False)
            )
            if bool(delivered):
                vs.add(int(out.v))
        assert vs <= set(range(cfg.n_parties + 1)) | {1}


class TestAttackDrawDistributions:
    def test_batched_draws_match_reference_laws(self):
        # SURVEY §4: statistical tests of the sampling laws, chi-square at
        # significance 1e-4.  Raw draws: actions uniform over 4
        # (tfg.py:272), coin uniform over 2 (tfg.py:274), rand_v uniform
        # over [0, nParties+1) (tfg.py:277).  Effective bitmask under
        # attack_scope="delivery" is therefore multinomial
        # {0: 1/8, drop: 1/8, forge: 1/4, clear-P: 1/4, clear-L: 1/4};
        # late ~ Bernoulli(p_late).
        from scipy import stats  # available via jax's scipy dependency

        from qba_tpu.adversary import raw_attack_draws

        cfg = QBAConfig(
            n_parties=5, size_l=4, n_dishonest=2,
            delivery="racy", p_late=0.3,
        )
        keys = jax.random.split(jax.random.key(0), 64)
        acts, coins, rvs, bits, lates = [], [], [], [], []
        for k in keys:
            a, c, rv = raw_attack_draws(cfg, k)
            att, rv_eff, late = sample_attacks_round(cfg, k)
            np.testing.assert_array_equal(np.asarray(rv_eff), np.asarray(rv))
            acts.append(np.asarray(a).ravel())
            coins.append(np.asarray(c).ravel())
            rvs.append(np.asarray(rv).ravel())
            bits.append(np.asarray(att).ravel())
            lates.append(np.asarray(late).ravel())
        acts = np.concatenate(acts)
        coins = np.concatenate(coins)
        rvs = np.concatenate(rvs)
        bits = np.concatenate(bits)
        lates = np.concatenate(lates)

        def chi2_uniform(x, k):
            obs = np.bincount(x, minlength=k)
            return stats.chisquare(obs).pvalue

        assert chi2_uniform(acts, 4) > 1e-4
        assert chi2_uniform(coins, 2) > 1e-4
        assert chi2_uniform(rvs, cfg.n_parties + 1) > 1e-4
        obs = np.array([(bits == b).sum() for b in (0, 1, 2, 4, 8)])
        assert obs.sum() == bits.size  # delivery scope: at most one bit
        exp = bits.size * np.array([1 / 8, 1 / 8, 1 / 4, 1 / 4, 1 / 4])
        assert stats.chisquare(obs, exp).pvalue > 1e-4
        rate = lates.mean()
        assert abs(rate - cfg.p_late) < 0.01


class TestBroadcastScope:
    """attack_scope="broadcast": the reference's shared-object mutation
    leak (tfg.py:271-284) — P.clear()/L.clear() persist across the
    recipient loop, a forged v carries forward until re-forged."""

    def _oracle(self, cfg, action, coin, rand_v):
        """Straight-line simulation of the reference's lieu_broadcast loop
        over the raw draws: returns expected (attack, rand_v) arrays at
        every non-self (cell, receiver)."""
        n_lieu, slots = cfg.n_lieutenants, cfg.slots
        n_pk = n_lieu * slots
        exp_bits = np.zeros((n_pk, n_lieu), np.int32)
        exp_rv = np.zeros((n_pk, n_lieu), np.int32)
        for cell in range(n_pk):
            sender = cell // slots
            cp = cl = False
            fv = None
            for r in range(n_lieu):  # rank order (tfg.py:267)
                if r == sender:
                    continue  # self skipped before drawing (tfg.py:268-269)
                a = int(action[cell, r])
                if a == 1:
                    fv = int(rand_v[cell, r])  # v reassigned (tfg.py:277)
                elif a == 2:
                    cp = True  # P.clear() persists (tfg.py:281)
                elif a == 3:
                    cl = True  # L.clear() persists (tfg.py:283)
                drop = a == 0 and int(coin[cell, r]) == 0
                exp_bits[cell, r] = (
                    (1 if drop else 0)
                    + (2 if fv is not None else 0)
                    + (4 if cp else 0)
                    + (8 if cl else 0)
                )
                exp_rv[cell, r] = fv if fv is not None else int(rand_v[cell, r])
        return exp_bits, exp_rv

    def test_effective_bits_match_reference_loop(self):

        cfg = QBAConfig(
            n_parties=7, size_l=4, n_dishonest=3, attack_scope="broadcast"
        )
        for seed in range(4):
            k = jax.random.key(seed)
            from qba_tpu.adversary import raw_attack_draws

            action, coin, rand_v = (
                np.asarray(x) for x in raw_attack_draws(cfg, k)
            )
            att, rv, _ = (
                np.asarray(x) for x in sample_attacks_round(cfg, k)
            )
            exp_bits, exp_rv = self._oracle(cfg, action, coin, rand_v)
            n_lieu, slots = cfg.n_lieutenants, cfg.slots
            for cell in range(n_lieu * slots):
                sender = cell // slots
                for r in range(n_lieu):
                    if r == sender:
                        continue  # engines never read self columns
                    assert att[cell, r] == exp_bits[cell, r], (cell, r)
                    if exp_bits[cell, r] & 2:
                        assert rv[cell, r] == exp_rv[cell, r], (cell, r)

    def test_leaked_edits_compose(self):
        # A broadcast-scope run must eventually deliver a packet with
        # multiple attack bits set — impossible under delivery scope.
        cfg = QBAConfig(
            n_parties=9, size_l=4, n_dishonest=4, attack_scope="broadcast"
        )
        seen_multi = False
        for seed in range(8):
            att, _, _ = sample_attacks_round(cfg, jax.random.key(seed))
            att = np.asarray(att)
            if ((att & (att - 1)) != 0).any():  # more than one bit set
                seen_multi = True
                break
        assert seen_multi
