"""Adversary model unit tests (``tfg.py:101-125,169-181,271-284``)."""

import jax
import jax.numpy as jnp
import numpy as np

from qba_tpu.adversary import (
    assign_dishonest,
    commander_orders,
    corrupt_at_delivery,
    sample_attacks_round,
)


from qba_tpu.config import QBAConfig
from qba_tpu.core import append_own
from qba_tpu.core.types import Packet, empty_evidence


def draws_for(cfg, key):
    """One cell's (action, coin, rand_v) from the batched round draws."""
    a, c, rv, _ = sample_attacks_round(cfg, key)
    return a[0, 0], c[0, 0], rv[0, 0]


class TestAssignDishonest:
    def test_counts_and_rank0_honest(self):
        cfg = QBAConfig(n_parties=11, size_l=4, n_dishonest=5)
        keys = jax.random.split(jax.random.key(0), 50)
        masks = jax.vmap(lambda k: assign_dishonest(cfg, k))(keys)
        assert masks.shape == (50, 12)
        assert bool(jnp.all(masks[:, 0]))  # QSD never dishonest
        np.testing.assert_array_equal(
            np.asarray(jnp.sum(~masks, axis=1)), np.full(50, 5)
        )

    def test_commander_can_be_dishonest(self):
        # tfg.py:105 draws from 1..nParties inclusive of the commander
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        keys = jax.random.split(jax.random.key(1), 200)
        masks = jax.vmap(lambda k: assign_dishonest(cfg, k))(keys)
        frac_comm_dishonest = float(jnp.mean(~masks[:, 1]))
        assert 0.15 < frac_comm_dishonest < 0.55  # ~1/3

    def test_zero_dishonest(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=0)
        assert bool(jnp.all(assign_dishonest(cfg, jax.random.key(2))))


class TestCommanderOrders:
    def test_honest_sends_same_v(self):
        cfg = QBAConfig(n_parties=11, size_l=4)
        v_sent, v = commander_orders(cfg, jax.random.key(0), jnp.asarray(True))
        assert bool(jnp.all(v_sent == v))
        assert 0 <= int(v) < cfg.w

    def test_dishonest_equivocates_at_split(self):
        cfg = QBAConfig(n_parties=11, size_l=4)
        found_split = False
        for i in range(20):
            v_sent, _ = commander_orders(
                cfg, jax.random.key(i), jnp.asarray(False)
            )
            vs = np.asarray(v_sent)
            # ranks 2..6 get v1, ranks 7..11 get v2, v1 != v2 (tfg.py:176-181)
            assert len(set(vs[:5])) == 1 and len(set(vs[5:])) == 1
            assert vs[0] != vs[5]
            found_split = True
        assert found_split

    def test_v2_uniform_over_not_v1(self):
        cfg = QBAConfig(n_parties=3, size_l=4)  # w = 4
        vs = []
        for i in range(600):
            v_sent, _ = commander_orders(cfg, jax.random.key(i), jnp.asarray(False))
            vs.append((int(v_sent[0]), int(v_sent[-1])))
        v2_given_v1 = {}
        for v1, v2 in vs:
            assert v1 != v2
            v2_given_v1.setdefault(v1, []).append(v2)
        for v1, v2s in v2_given_v1.items():
            counts = np.bincount(v2s, minlength=4)
            assert counts[v1] == 0
            assert (counts[[i for i in range(4) if i != v1]] > 10).all()


class TestCorruptAtDelivery:
    def _packet(self, cfg):
        ev = append_own(
            empty_evidence(cfg.max_l, cfg.size_l),
            jnp.asarray([True, True, False, False]),
            jnp.asarray([2, 3, 0, 0], dtype=jnp.int32),
        )
        return Packet(
            p_mask=jnp.asarray([True, True, False, False]),
            v=jnp.asarray(1, jnp.int32),
            evidence=ev,
        )

    def test_honest_sender_untouched(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        for i in range(10):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(True)
            )
            assert bool(delivered)
            assert int(out.v) == 1
            assert out.p_mask.tolist() == pk.p_mask.tolist()
            assert int(out.evidence.count) == 1

    def test_dishonest_actions_all_occur(self):
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        seen = {"drop": 0, "v": 0, "p": 0, "l": 0, "clean": 0}
        for i in range(400):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(False)
            )
            if not bool(delivered):
                seen["drop"] += 1
            elif int(out.v) != 1:
                seen["v"] += 1
            elif not bool(jnp.any(out.p_mask)):
                seen["p"] += 1
            elif int(out.evidence.count) == 0:
                seen["l"] += 1
            else:
                seen["clean"] += 1
        # actions are ~25% each; drop additionally flips a fair coin
        # (tfg.py:274), so ~12.5% of deliveries vanish; corrupt-v draws
        # from [0, nParties+1) and can coincide with the original v
        assert seen["drop"] > 25
        assert seen["v"] > 60
        assert seen["p"] > 60
        assert seen["l"] > 60

    def test_corrupt_v_range(self):
        # tfg.py:277: random order from [0, nParties+1), NOT [0, w)
        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=1)
        pk = self._packet(cfg)
        vs = set()
        for i in range(600):
            out, delivered = corrupt_at_delivery(
                cfg, draws_for(cfg, jax.random.key(i)), pk, jnp.asarray(False)
            )
            if bool(delivered):
                vs.add(int(out.v))
        assert vs <= set(range(cfg.n_parties + 1)) | {1}


class TestAttackDrawDistributions:
    def test_batched_draws_match_reference_laws(self):
        # SURVEY §4: statistical tests of the sampling laws.  Actions
        # uniform over 4 (tfg.py:272), coin uniform over 2 (tfg.py:274),
        # rand_v uniform over [0, nParties+1) (tfg.py:277), late ~
        # Bernoulli(p_late).  Chi-square over the pooled per-round draws.
        from scipy import stats  # available via jax's scipy dependency

        cfg = QBAConfig(
            n_parties=5, size_l=4, n_dishonest=2,
            delivery="racy", p_late=0.3,
        )
        keys = jax.random.split(jax.random.key(0), 64)
        acts, coins, rvs, lates = [], [], [], []
        for k in keys:
            a, c, rv, late = sample_attacks_round(cfg, k)
            acts.append(np.asarray(a).ravel())
            coins.append(np.asarray(c).ravel())
            rvs.append(np.asarray(rv).ravel())
            lates.append(np.asarray(late).ravel())
        acts = np.concatenate(acts)
        coins = np.concatenate(coins)
        rvs = np.concatenate(rvs)
        lates = np.concatenate(lates)

        def chi2_uniform(x, k):
            obs = np.bincount(x, minlength=k)
            return stats.chisquare(obs).pvalue

        assert chi2_uniform(acts, 4) > 1e-4
        assert chi2_uniform(coins, 2) > 1e-4
        assert chi2_uniform(rvs, cfg.n_parties + 1) > 1e-4
        rate = lates.mean()
        assert abs(rate - cfg.p_late) < 0.01
