"""CLI and checkpoint-resumable sweep tests."""

import io
import os
import json

import pytest

from qba_tpu.cli import main
from qba_tpu.config import QBAConfig
from qba_tpu.sweep import chunk_keys, load_checkpoint, run_sweep


class TestSweep:
    def test_aggregates_honest(self):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=4)
        res = run_sweep(cfg, n_chunks=3, chunk_trials=4)
        assert res.n_trials == 12
        assert res.success_rate == 1.0
        assert res.resumed_chunks == 0

    def test_checkpoint_resume_identical(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=4, seed=3)
        ckpt = str(tmp_path / "sweep.json")

        full = run_sweep(cfg, n_chunks=4, chunk_trials=4)

        # Partial run writes the checkpoint...
        part = run_sweep(cfg, n_chunks=2, chunk_trials=4, checkpoint=ckpt)
        assert len(load_checkpoint(ckpt, cfg, 4)) == 2
        # ...resume completes the remaining chunks only.
        res = run_sweep(cfg, n_chunks=4, chunk_trials=4, checkpoint=ckpt)
        assert res.resumed_chunks == 2
        assert [c.successes for c in res.chunks] == [
            c.successes for c in full.chunks
        ]
        assert part.chunks == full.chunks[:2]

    def test_checkpoint_rejects_config_mismatch(self, tmp_path):
        ckpt = str(tmp_path / "sweep.json")
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=2)
        run_sweep(cfg, n_chunks=1, chunk_trials=2, checkpoint=ckpt)
        other = QBAConfig(n_parties=3, size_l=8, n_dishonest=1, trials=2)
        with pytest.raises(ValueError, match="different config"):
            load_checkpoint(ckpt, other, 2)
        with pytest.raises(ValueError, match="chunk_trials"):
            load_checkpoint(ckpt, cfg, 3)

    def test_resume_with_fewer_chunks_aggregates_subset(self, tmp_path):
        cfg = QBAConfig(n_parties=3, size_l=8, n_dishonest=0, trials=2)
        ckpt = str(tmp_path / "sweep.json")
        run_sweep(cfg, n_chunks=4, chunk_trials=2, checkpoint=ckpt)
        res = run_sweep(cfg, n_chunks=2, chunk_trials=2, checkpoint=ckpt)
        assert res.n_trials == 4  # only the requested 2 chunks
        assert res.resumed_chunks == 2
        # the checkpoint file still holds all 4 chunks
        assert len(load_checkpoint(ckpt, cfg, 2)) == 4

    def test_chunk_keys_deterministic(self):
        cfg = QBAConfig(n_parties=3, size_l=4, seed=9)
        a = chunk_keys(cfg, 5, 3)
        b = chunk_keys(cfg, 5, 3)
        assert (a == b).all()


class TestCLI:
    def test_run_honest_verdicts(self):
        out = io.StringIO()
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--trials", "2"],
            out=out,
        )
        text = out.getvalue()
        assert rc == 0
        assert text.count("Success:    True") == 2
        assert "success rate: 1.0000" in text

    def test_run_local_backend(self, tmp_path):
        out = io.StringIO()
        jsonl = tmp_path / "events.jsonl"
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--trials", "1",
             "--backend", "local", "--jsonl", str(jsonl)],
            out=out,
        )
        assert rc == 0
        assert "Success:    True" in out.getvalue()
        # --jsonl must be honored on every backend
        assert json.loads(jsonl.read_text().splitlines()[0])["phase"] == "config"

    def test_jax_backend_trail_replay(self, tmp_path):
        # VERDICT r2 item 6: the default (vectorized) backend replays
        # displayed trials through the local backend for the per-packet
        # trail; replay decisions must match the vectorized verdicts.
        out = io.StringIO()
        jsonl = tmp_path / "events.jsonl"
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--n-dishonest",
             "1", "--trials", "2", "--jsonl", str(jsonl)],
            out=out,
        )
        assert rc == 0
        events = [
            json.loads(line) for line in jsonl.read_text().splitlines()
        ]
        # The full protocol trail is present (a log_d_3-class run).
        msgs = {(e["phase"], e["message"]) for e in events}
        assert ("round", "receive") in msgs
        assert ("decision", "verdict") in msgs
        # No differential breach between replay and vectorized results.
        assert ("decision", "trail replay mismatch") not in msgs

    def test_run_quirk_mode_flags(self):
        # --attack-scope / --racy-mode / --delivery flow into QBAConfig.
        out = io.StringIO()
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--n-dishonest",
             "1", "--trials", "1", "--attack-scope", "broadcast",
             "--delivery", "racy", "--p-late", "0.3", "--racy-mode",
             "defer", "--backend", "local"],
            out=out,
        )
        assert rc == 0
        assert "Decisions:" in out.getvalue()

    def test_run_rejects_invalid_quirk_combo(self):
        out = io.StringIO()
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--trials", "1",
             "--racy-mode", "defer"],  # defer without --delivery racy
            out=out,
        )
        assert rc != 0

    def test_bench_json(self):
        out = io.StringIO()
        rc = main(
            ["bench", "--n-parties", "3", "--size-l", "4", "--trials", "8",
             "--reps", "1"],
            out=out,
        )
        assert rc == 0
        rec = json.loads(out.getvalue())
        assert rec["metric"] == "protocol_rounds_per_sec"
        assert rec["value"] > 0

    def test_bench_resource_gen_json(self):
        out = io.StringIO()
        rc = main(
            ["bench", "--scenario", "resource_gen", "--n-parties", "5",
             "--size-l", "8", "--trials", "4", "--reps", "1",
             "--qsim-path", "stabilizer"],
            out=out,
        )
        assert rc == 0
        rec = json.loads(out.getvalue())
        assert rec["metric"] == "resource_shots_per_sec"
        assert rec["value"] > 0
        assert rec["qsim"] == "stabilizer/gf2-batched"
        assert rec["shots_per_rep"] == 4 * 8
        assert rec["config"]["qsim_path"] == "stabilizer"

    def test_sweep_with_checkpoint(self, tmp_path):
        ckpt = str(tmp_path / "c.json")
        args = ["sweep", "--n-parties", "3", "--size-l", "4", "--trials", "4",
                "--n-chunks", "2", "--checkpoint", ckpt]
        out = io.StringIO()
        assert main(args, out=out) == 0
        assert "trials: 8" in out.getvalue()
        # second invocation resumes fully from the checkpoint
        out2 = io.StringIO()
        assert main(args, out=out2) == 0
        assert "resumed from checkpoint" in out2.getvalue()

    def test_sweep_target_prints_stop_line(self):
        # --target turns --n-chunks into a ceiling: the honest config
        # decides vs 1/3 within the first chunk, and the CLI reports the
        # typed stop with its anytime-valid interval.
        out = io.StringIO()
        rc = main(
            ["sweep", "--n-parties", "3", "--size-l", "8", "--n-dishonest",
             "0", "--trials", "16", "--n-chunks", "8",
             "--target", "decide vs 1/3 @ 95%"],
            out=out,
        )
        text = out.getvalue()
        assert rc == 0
        assert "stop: decided_above after" in text
        assert "95% CI [" in text
        assert "trials: 16" in text  # 1 of the 8 budgeted chunks ran

    def test_sweep_resume_force_recovers_chunk_trials_mismatch(self, tmp_path):
        ckpt = str(tmp_path / "c.json")
        base = ["sweep", "--n-parties", "3", "--size-l", "4",
                "--n-chunks", "2", "--checkpoint", ckpt]
        assert main(base + ["--trials", "4"], out=io.StringIO()) == 0
        # chunk_trials disagreement without the escape hatch: clean rc-2
        # (QBACheckpointMismatch is a ValueError).
        assert main(base + ["--trials", "8"], out=io.StringIO()) == 2
        # --resume-force discards the checkpoint with a recorded warning
        # and re-chunks from scratch.
        out = io.StringIO()
        with pytest.warns(Warning, match="resume-force"):
            rc = main(base + ["--trials", "8", "--resume-force"], out=out)
        assert rc == 0
        assert "trials: 16" in out.getvalue()
        # A config mismatch is never forceable — those chunks answer a
        # different question.
        rc = main(
            base + ["--trials", "8", "--n-dishonest", "1", "--resume-force"],
            out=io.StringIO(),
        )
        assert rc == 2

    def test_invalid_config_clean_error(self):
        rc = main(
            ["run", "--n-parties", "3", "--size-l", "8", "--n-dishonest", "9"],
            out=io.StringIO(),
        )
        assert rc == 2


class TestDeviceAwareRunner:
    def test_sharded_default_matches_single_device(self):
        # On the 8-device test mesh the default runner dp-shards chunks;
        # results must equal the explicit single-device batch.
        from qba_tpu.backends.jax_backend import batched_trials
        from qba_tpu.sweep import run_sweep

        cfg = QBAConfig(n_parties=4, size_l=8, n_dishonest=1, trials=16)
        a = run_sweep(cfg, n_chunks=2)  # device-aware default
        b = run_sweep(cfg, n_chunks=2, runner=batched_trials)
        assert a.successes == b.successes
        assert a.n_trials == b.n_trials

    def test_indivisible_chunk_falls_back(self):
        from qba_tpu.sweep import run_sweep

        cfg = QBAConfig(n_parties=3, size_l=4, n_dishonest=0, trials=7)
        res = run_sweep(cfg, n_chunks=2)  # 7 % 8 != 0 -> vmap fallback
        assert res.n_trials == 14


class TestStudyCommand:
    def test_study_sweeps_param_and_plots(self, tmp_path):
        pytest.importorskip("matplotlib")
        out = io.StringIO()
        png = str(tmp_path / "study.png")
        rc = main(
            [
                "study", "--n-parties", "3", "--size-l", "4",
                "--n-dishonest", "1", "--trials", "8",
                "--param", "size_l", "--values", "2,4", "--plot", png,
            ],
            out=out,
        )
        assert rc == 0
        text = out.getvalue()
        assert "size_l=2: success_rate=" in text
        assert "size_l=4: success_rate=" in text
        assert os.path.exists(png)

    def test_study_p_late_forces_racy(self):
        out = io.StringIO()
        rc = main(
            [
                "study", "--n-parties", "3", "--size-l", "4",
                "--n-dishonest", "1", "--trials", "8",
                "--param", "p_late", "--values", "0.0,0.5",
            ],
            out=out,
        )
        assert rc == 0
        assert "p_late=0.5: success_rate=" in out.getvalue()

    def test_study_rejects_unknown_param(self):
        with pytest.raises(SystemExit):
            main(["study", "--n-parties", "3", "--size-l", "4",
                  "--param", "w", "--values", "1,2"])


class TestBatchCeilingDiagnostic:
    def test_hbm_oom_is_named_not_raw(self, monkeypatch):
        # KI-2: a compile-time HBM OOM (possibly wrapped in the remote
        # helper's HTTP 500) must surface as a named ceiling with the
        # chunking remedy, not a bare helper crash.
        import qba_tpu.backends.jax_backend as jb
        from qba_tpu.benchmark import measure_batch
        from qba_tpu.config import QBAConfig

        def oom(cfg, keys=None):
            raise RuntimeError(
                "INTERNAL: http://127.0.0.1:1/remote_compile: HTTP 500: "
                "tpu_compile_helper subprocess exit code 1 ... XLA:TPU "
                "compile permanent error. Ran out of memory in memory "
                "space hbm. Used 21.02G of 15.75G hbm."
            )

        monkeypatch.setattr(jb, "run_trials", oom)
        cfg = QBAConfig(n_parties=3, size_l=4, trials=8)
        with pytest.raises(RuntimeError, match="KI-2"):
            measure_batch(cfg, reps=1)

    def test_non_oom_errors_pass_through(self, monkeypatch):
        import qba_tpu.backends.jax_backend as jb
        from qba_tpu.benchmark import measure_batch
        from qba_tpu.config import QBAConfig

        def other(cfg, keys=None):
            raise RuntimeError("some unrelated lowering failure")

        monkeypatch.setattr(jb, "run_trials", other)
        cfg = QBAConfig(n_parties=3, size_l=4, trials=8)
        with pytest.raises(RuntimeError, match="unrelated"):
            measure_batch(cfg, reps=1)


class TestDeviceBatchMeasure:
    def test_slope_measure_runs_and_shapes(self):
        # The slope method itself (chain r batches, one fence, difference
        # quotient) must run on any backend; on CPU the "device" time is
        # just compute time, but shapes/validation are backend-neutral.
        from qba_tpu.benchmark import measure_device_batch
        from qba_tpu.config import QBAConfig

        cfg = QBAConfig(n_parties=3, size_l=4, trials=8)
        slopes, n_run = measure_device_batch(
            cfg, pairs=2, reps_lo=1, reps_hi=2
        )
        assert len(slopes) == 2 and n_run == 8
        assert all(isinstance(s, float) for s in slopes)

    def test_slope_measure_validation(self):
        import pytest as _pytest

        from qba_tpu.benchmark import measure_device_batch
        from qba_tpu.config import QBAConfig

        cfg = QBAConfig(n_parties=3, size_l=4, trials=8)
        with _pytest.raises(ValueError, match="pairs"):
            measure_device_batch(cfg, pairs=0)
        with _pytest.raises(ValueError, match="reps_lo"):
            measure_device_batch(cfg, reps_lo=3, reps_hi=2)
