"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (from BASELINE.json): protocol rounds/sec at nParties=11,
sizeL=64, 1000 trials (nDishonest=3 → 4 voting rounds/trial) on the jax
backend.  ``vs_baseline_wall`` / ``vs_baseline_device`` are labeled
speedups over the message-level pure-Python reference backend
(:mod:`qba_tpu.backends.local_backend`) run on host CPU — the in-repo
stand-in for the reference's ``mpiexec`` run (the reference itself
publishes no numbers and needs MPI + qsimov, neither available here;
BASELINE.md).  The wall ratio is like-for-like; the device ratio is the
kernels-only upper bound (tunnel overhead excluded from the numerator
only).

The single JSON line is variance-aware: it carries every rep's wall time
(``rep_seconds``) plus the median-derived value next to the best-of
headline, so round-over-round drift can be distinguished from the
documented 10-15% tunnel noise without cross-referencing docs/PERF.md.
It also embeds the north-star gate metric (BASELINE.md config 5:
nParties=33, sizeL=64, nDishonest=10, 1000 trials, lossless) under
``northstar`` — both gate metrics land in ``BENCH_r*.json`` each round.

Usage: ``python bench.py`` (env ``QBA_BENCH_QUICK=1`` for a small dev run).
"""

from __future__ import annotations

import json
import os
import statistics
import sys


def _measure_jax(cfg, reps: int, chunk_trials: int | None = None):
    """Per-rep wall seconds + actual trial count for one Monte-Carlo
    batch — the shared chunk/key/fence recipe
    (:func:`qba_tpu.benchmark.measure_batch`; fresh keys per rep defeat
    the tunnel's result cache, chunking respects the HBM ceiling)."""
    from qba_tpu.benchmark import measure_batch

    times, n_run, _results = measure_batch(cfg, reps, chunk_trials)
    return times, n_run


def _measure_local(cfg, n_trials: int) -> float:
    """Per-trial seconds for the pure-Python reference backend.

    Runs in a CPU-platform subprocess: the backend issues thousands of
    tiny per-packet jax dispatches, which must not ride the TPU tunnel
    (and mirrors the reference's host-CPU execution, BASELINE.md).
    """
    import subprocess

    code = f"""
import time, jax
jax.config.update("jax_platforms", "cpu")
from qba_tpu.backends.jax_backend import trial_keys
from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.config import QBAConfig
cfg = QBAConfig(n_parties={cfg.n_parties}, size_l={cfg.size_l},
                n_dishonest={cfg.n_dishonest}, trials={cfg.trials},
                seed={cfg.seed})
keys = trial_keys(cfg)
run_trial_local(cfg, keys[0])
t0 = time.perf_counter()
for i in range({n_trials}):
    run_trial_local(cfg, keys[i % cfg.trials])
print((time.perf_counter() - t0) / {n_trials})
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(f"baseline subprocess failed: {proc.stderr[-500:]}")
    return float(proc.stdout.strip().splitlines()[-1])


def _rps_stats(cfg, times: list[float], n_run: int) -> dict:
    """Best/median rounds-per-second view of one rep series."""
    total_rounds = n_run * cfg.n_rounds
    best = min(times)
    med = statistics.median(times)
    return {
        "value": round(total_rounds / best, 2),
        "median_value": round(total_rounds / med, 2),
        "reps": len(times),
        "rep_seconds": [round(t, 4) for t in times],
    }


def _measure_device(
    cfg, quick: bool, chunk_trials: int | None = None, reps_hi: int = 5
):
    """Device-side stats via the slope method (VERDICT r4 item 4):
    per-batch device seconds with the tunnel's dispatch + fetch
    overhead cancelled.  Returns a dict with the median-based
    ``device_rounds_per_sec`` (the honest gate number), the per-pair
    slope estimates, and their relative spread.  ``reps_hi`` sets the
    slope baseline length — short-batch configs need a longer chain so
    the slope signal dwarfs the tunnel's ~30 ms jitter."""
    from qba_tpu.benchmark import measure_device_batch

    slopes, n_run = measure_device_batch(
        cfg,
        pairs=2 if quick else 4,
        reps_lo=1,
        reps_hi=3 if quick else reps_hi,
        chunk_trials=chunk_trials,
        warmup=False,  # callers already warmed this config's jit cache
    )
    total_rounds = n_run * cfg.n_rounds
    med = statistics.median(slopes)
    if med <= 0:
        # Jitter can drive t_hi < t_lo on tiny batches; a negative
        # "device throughput" must never become the gate headline —
        # fail the measurement so the caller falls back to wall median.
        raise RuntimeError(
            f"device slope measurement degenerate (median {med:.4f}s "
            f"<= 0 across {slopes}); tunnel jitter swamped the batch"
        )
    return {
        "device_rounds_per_sec": round(total_rounds / med, 2),
        "device_seconds_per_batch": [round(s, 4) for s in slopes],
        "device_spread": round((max(slopes) - min(slopes)) / med, 4),
    }


def _mega_vs_fused(quick: bool) -> list[dict]:
    """Round-8/round-11 launch-overhead decomposition: the same
    Monte-Carlo batch timed under three engine variants, same keys,
    same trial count, on the stabilizer sampler so the measured trial
    includes step-1 resource generation:

    - ``pallas_fused`` — one launch per round, host-side generation;
    - ``pallas_mega`` (``mega_gen="host"``) — one launch per trial,
      host-side generation (the round-8 comparison point);
    - ``pallas_mega_gen`` (``mega_gen="gf2"``) — one launch per trial
      INCLUDING generation (the round-11 in-VMEM GF(2) prologue).

    Because all three are bit-identical (the megakernel equivalence
    tests), the wall-time gap divided by the launch-count gap is a
    direct per-launch fixed-overhead estimate; ``fixed_overhead_share``
    is the round-8 share (host-gen mega vs fused) and
    ``gen_inclusive_overhead_share`` is the round-11 headline — the
    fraction of the fused engine's generation-inclusive trial time the
    fully-fused launch eliminates.

    Config points: the headline shape (11p/L64), a launch-bound shape
    (17p/L16: 5 rounds of tiny kernels — overhead-dominated), 33p/L8
    (the row records the honest demotion: the per-round working set
    alone crowds the mega budget), and the north-star (33p/L64) gated
    to TPU (``QBA_BENCH_NS=1`` overrides) because off-TPU both engines
    run minutes-slow in interpret mode.

    Standing caveat (docs/PERF.md): off-TPU these numbers come from the
    Pallas interpreter on CPU — valid for RELATIVE overhead share with
    the same CPU-fenced methodology, not absolute throughput."""
    import dataclasses

    import jax

    from qba_tpu.config import QBAConfig

    on_tpu = jax.default_backend() == "tpu"
    points = [
        ("n11_l64_d3", dict(n_parties=11, size_l=64, n_dishonest=3)),
        # Launch-bound: 5 rounds of small kernels, megakernel live.
        ("n17_l16_d4", dict(n_parties=17, size_l=16, n_dishonest=4)),
        # 11 rounds but the per-round working set alone crowds the
        # 64 MiB mega budget — the row records the honest demotion.
        ("n33_l8_d10", dict(n_parties=33, size_l=8, n_dishonest=10)),
    ]
    if on_tpu or os.environ.get("QBA_BENCH_NS") == "1":
        points.append(
            ("northstar_n33_l64_d10",
             dict(n_parties=33, size_l=64, n_dishonest=10)),
        )
    trials = 4 if quick else (64 if on_tpu else 16)
    reps = 2 if quick else 4
    variants = (
        ("pallas_fused", "pallas_fused", "host"),
        ("pallas_mega", "pallas_mega", "host"),
        ("pallas_mega_gen", "pallas_mega", "gf2"),
    )
    rows = []
    for label, kw in points:
        row: dict = {"config": label, "trials": trials}
        try:
            from qba_tpu.benchmark import engine_description, kernel_plan

            per = {}
            for name, eng, gen in variants:
                cfg = QBAConfig(
                    **kw, trials=trials, seed=0, qsim_path="stabilizer"
                )
                cfg = dataclasses.replace(
                    cfg, round_engine=eng, mega_gen=gen
                )
                times, n_run = _measure_jax(cfg, reps=reps)
                plan = kernel_plan(cfg)
                per[name] = {
                    "median_seconds": round(statistics.median(times), 4),
                    "rep_seconds": [round(t, 4) for t in times],
                    "engine": engine_description(cfg),
                    "launches_per_trial": plan["launches_per_trial"],
                    "mega_gen": plan["mega_gen"],
                }
                row[name] = per[name]
            t_m = per["pallas_mega"]["median_seconds"]
            t_f = per["pallas_fused"]["median_seconds"]
            t_g = per["pallas_mega_gen"]["median_seconds"]
            l_m = per["pallas_mega"]["launches_per_trial"]
            l_f = per["pallas_fused"]["launches_per_trial"]
            if None not in (l_m, l_f) and l_f > l_m and t_f > 0:
                row["per_launch_overhead_s"] = round(
                    max(t_f - t_m, 0.0) / (trials * (l_f - l_m)), 6
                )
                row["fixed_overhead_share"] = round(
                    max(1.0 - t_m / t_f, 0.0), 4
                )
            if t_f > 0 and per["pallas_mega_gen"]["mega_gen"] == "gf2":
                row["gen_inclusive_overhead_share"] = round(
                    max(1.0 - t_g / t_f, 0.0), 4
                )
            row["methodology"] = (
                "cpu-fenced interpret-mode, generation-inclusive "
                "stabilizer trials (relative share only)"
                if not on_tpu
                else "tpu, fence-at-end, generation-inclusive "
                "stabilizer trials"
            )
        except Exception as e:  # comparison must never sink the gate
            row["error"] = repr(e)[:300]
        rows.append(row)
        print(f"mega_vs_fused {label}: {row}", file=sys.stderr)
    return rows


def _multichip(quick: bool) -> dict:
    """Multichip scenario (``QBA_BENCH_SCENARIO=multichip``): a dp×tp
    sweep over the 8 emulated devices — (8,1), (4,2), (2,4), (1,8) —
    timing the party-sharded engine under ring comms per shape, next
    to the sharded KI-2 model's per-device/mesh trial ceilings for the
    north-star shape at that tp width.  The rows are the CPU-fenced
    template for the first real-TPU MULTICHIP_r06 capture: on hardware
    the same sweep attributes ring remote-DMA hops instead of
    ``ppermute`` and the ceilings become admissible batch sizes.

    Runs in a subprocess so ``XLA_FLAGS=--xla_force_host_platform_
    device_count=8`` lands before jax import regardless of what the
    parent process already initialized.  Standing caveat (docs/PERF.md):
    off-TPU, absolute rounds/s is interpret/CPU-fenced — valid for
    RELATIVE shape-to-shape comparison only."""
    import subprocess

    trials = 8 if quick else 32
    reps = 2 if quick else 4
    code = f"""
import dataclasses, json, statistics, time, warnings
import jax
from qba_tpu.config import QBAConfig
from qba_tpu.analysis.launches import spmd_launches_per_trial
from qba_tpu.analysis.memory import sharded_trial_ceiling
from qba_tpu.benchmark import engine_description
from qba_tpu.parallel import make_mesh, run_trials_spmd
from qba_tpu.backends.jax_backend import trial_keys

cfg = QBAConfig(n_parties=17, size_l=16, n_dishonest=4,
                trials={trials}, seed=0)
ns = QBAConfig(33, 64, 10)
on_tpu = jax.default_backend() == "tpu"

def timed(run_cfg, mesh):
    keys = trial_keys(run_cfg)
    run_trials_spmd(run_cfg, mesh, keys)  # warm the jit cache
    times = []
    for _ in range({reps}):
        t0 = time.perf_counter()
        res = run_trials_spmd(run_cfg, mesh, keys)
        jax.block_until_ready(res.trials.success)
        times.append(time.perf_counter() - t0)
    return times

rows = []
for dp, tp in ((8, 1), (4, 2), (2, 4), (1, 8)):
    mesh = make_mesh({{"dp": dp, "tp": tp}})
    times = timed(cfg, mesh)
    med = statistics.median(times)
    model = sharded_trial_ceiling(ns, dp=dp, tp=tp, comms="ring")
    model_ag = sharded_trial_ceiling(ns, dp=dp, tp=tp,
                                     comms="all_gather")
    row = {{
        "mesh": {{"dp": dp, "tp": tp}},
        "engine": engine_description(cfg, tp=tp) if tp > 1
                  else engine_description(cfg),
        "trials": cfg.trials,
        "rounds_per_sec": round(cfg.trials * cfg.n_rounds / med, 2),
        "rep_seconds": [round(t, 4) for t in times],
        "northstar_per_device_ceiling": model["per_device_trials"],
        "northstar_mesh_ceiling": model["mesh_trials"],
        "northstar_all_gather_per_device": model_ag["per_device_trials"],
    }}
    if tp > 1:
        # Round-11 row: the party-sharded megakernel (in-kernel ring,
        # one launch per trial on TPU; off-TPU it times the fused
        # transport twin — same pool movement, per-round launches).
        mcfg = dataclasses.replace(cfg, round_engine="pallas_mega")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mtimes = timed(mcfg, mesh)
            mdesc = engine_description(mcfg, tp=tp)
        mmed = statistics.median(mtimes)
        row["sharded_mega"] = {{
            "engine": mdesc,
            "rounds_per_sec": round(
                cfg.trials * cfg.n_rounds / mmed, 2),
            "rep_seconds": [round(t, 4) for t in mtimes],
            "launches_per_trial": spmd_launches_per_trial(
                cfg, "pallas_mega", "ring", 4, tpu=on_tpu),
            "launches_per_trial_tpu_model": spmd_launches_per_trial(
                cfg, "pallas_mega", "ring", 4, tpu=True),
            "in_kernel_ring_hops_tpu_model":
                4 * cfg.n_rounds * (tp - 1),
        }}
    rows.append(row)
print(json.dumps(rows))
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=1200,
        env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"multichip subprocess failed: {proc.stderr[-800:]}"
        )
    rows = json.loads(proc.stdout.strip().splitlines()[-1])
    for row in rows:
        print(f"multichip {row['mesh']}: {row['rounds_per_sec']} "
              f"rounds/s ({row['engine']})", file=sys.stderr)
    return {
        "metric": "multichip_rounds_per_sec_n17_l16_d4",
        "scenario": "multichip",
        "unit": "rounds/s",
        "rows": rows,
        "methodology": (
            "8 emulated CPU devices (XLA_FLAGS force_host_platform_"
            "device_count); ring comms via ppermute — relative "
            "shape-to-shape comparison only, ceilings are the v5e "
            "north-star model"
        ),
    }


def _targeted(quick: bool) -> dict:
    """Targeted scenario (``QBA_BENCH_SCENARIO=targeted``): time-to-
    decision for the same precision target under the host per-chunk
    loop vs the device-resident single-dispatch loop (ROADMAP item 3),
    at the headline shape.  The two runs consume identical keys and by
    the stop-table construction stop at the same chunk boundary — the
    row records both the p50 wall seconds and the dispatch counts
    (host: one per executed chunk; device: exactly one), which is the
    actual quantity the device loop collapses.  Standing caveat
    (docs/PERF.md): off-TPU the wall numbers are CPU/interpret-fenced —
    valid for host-vs-device RELATIVE comparison at the same shape,
    not absolute latency."""
    import statistics
    import time

    from qba_tpu.config import QBAConfig
    from qba_tpu.sweep import run_sweep

    cfg = QBAConfig(
        n_parties=11,
        size_l=16 if quick else 64,
        n_dishonest=3,
        trials=8 if quick else 64,  # chunk_trials
        seed=0,
    )
    n_chunks = 8 if quick else 32
    reps = 2 if quick else 4
    specs = [
        "decide vs 1/3 @ 95%",
        "ci_width<=0.25" if quick else "ci_width<=0.12",
    ]
    rows = []
    for spec in specs:
        row: dict = {
            "target": spec,
            "budget_chunks": n_chunks,
            "chunk_trials": cfg.trials,
        }
        try:
            per: dict = {}
            for mode in ("host", "device"):
                run_sweep(  # warm the jit cache for this mode
                    cfg, n_chunks=n_chunks, chunk_trials=cfg.trials,
                    target=spec, dispatch=mode,
                )
                times = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    res = run_sweep(
                        cfg, n_chunks=n_chunks, chunk_trials=cfg.trials,
                        target=spec, dispatch=mode,
                    )
                    times.append(time.perf_counter() - t0)
                per[mode] = {
                    "p50_time_to_decision_s": round(
                        statistics.median(times), 4
                    ),
                    "rep_seconds": [round(t, 4) for t in times],
                    # Host pays one dispatch + one fenced readback per
                    # executed chunk; the device loop is one dispatch
                    # and one readback regardless of where it stops.
                    "dispatches": 1 if mode == "device" else len(res.chunks),
                    "stop_chunk": len(res.chunks),
                    "stop_reason": res.stop.reason if res.stop else None,
                    "n_trials": res.stop.n_trials if res.stop else None,
                }
                row[mode] = per[mode]
            row["stop_chunk_agrees"] = (
                per["host"]["stop_chunk"] == per["device"]["stop_chunk"]
                and per["host"]["stop_reason"] == per["device"]["stop_reason"]
            )
        except Exception as e:  # a row must never sink the artifact
            row["error"] = repr(e)[:300]
        rows.append(row)
        print(f"targeted {spec}: {row}", file=sys.stderr)
    return {
        "metric": (
            f"targeted_time_to_decision_n{cfg.n_parties}_l{cfg.size_l}"
            f"_d{cfg.n_dishonest}"
        ),
        "scenario": "targeted",
        "unit": "s",
        "rows": rows,
        "methodology": (
            "host loop (dispatch+fenced readback per chunk) vs "
            "device-resident while_loop (one dispatch), identical keys "
            "and stop boundary by construction; off-TPU wall times are "
            "CPU-fenced — relative comparison only"
        ),
    }


def main() -> None:
    from qba_tpu.compile_cache import enable_compile_cache
    from qba_tpu.config import QBAConfig
    from qba_tpu.diagnostics import add_decision_hook, remove_decision_hook
    from qba_tpu.obs.manifest import probe_stats_snapshot

    enable_compile_cache()

    if os.environ.get("QBA_BENCH_SCENARIO") == "multichip":
        # The dp×tp sweep replaces the single-device battery: its own
        # JSON line is the whole artifact (CI uploads it as
        # MULTICHIP_r*.json).
        print(json.dumps(
            _multichip(os.environ.get("QBA_BENCH_QUICK") == "1")
        ))
        return

    if os.environ.get("QBA_BENCH_SCENARIO") == "targeted":
        # Host-vs-device time-to-decision at the headline shape: its
        # own JSON line is the whole artifact (CI uploads it as
        # TARGETED_r*.json next to BENCH_r*.json).
        print(json.dumps(
            _targeted(os.environ.get("QBA_BENCH_QUICK") == "1")
        ))
        return

    # Live dispatch-decision capture + probe-counter baseline for the
    # manifest embedded in the JSON line (docs/OBSERVABILITY.md).
    decisions: list = []
    _hook = add_decision_hook(decisions.append)
    stats_before = probe_stats_snapshot()

    quick = os.environ.get("QBA_BENCH_QUICK") == "1"
    cfg = QBAConfig(
        n_parties=11,
        size_l=64,
        n_dishonest=3,
        trials=64 if quick else 1000,
        seed=0,
    )

    # 8 reps: the remote-tunnel result fetch has ~30 ms of run-to-run
    # jitter on top of a ~60 ms floor (and the floor itself drifts by
    # tens of ms over minutes on the shared tunnel), so extra full-work
    # reps make the best-of estimate much less noisy — and the full rep
    # series now lands in the JSON so the artifact shows the spread.
    times, n_run = _measure_jax(cfg, reps=2 if quick else 8)
    stats = _rps_stats(cfg, times, n_run)
    rps = stats["value"]
    print(
        f"jax: {cfg.trials} trials best {min(times):.3f}s -> {rps:.1f} "
        f"rounds/s (median {stats['median_value']:.1f})",
        file=sys.stderr,
    )
    # Device-side view (VERDICT r4 item 4): the slope method cancels
    # the tunnel's per-rep fetch jitter; its MEDIAN is the headline.
    try:
        # reps_hi=9: ~60 ms device batches need a ~0.5 s slope baseline
        # to push the tunnel's ~30 ms jitter under 10% spread.
        device = _measure_device(cfg, quick, reps_hi=9)
        print(
            f"device: {device['device_rounds_per_sec']:.1f} rounds/s "
            f"(spread {device['device_spread']:.1%})",
            file=sys.stderr,
        )
    except Exception as e:  # wall headline must still flow
        print(f"device measurement failed: {e!r}", file=sys.stderr)
        device = None

    baseline_trials = 2 if quick else 4
    try:
        per_trial = _measure_local(cfg, baseline_trials)
        baseline_rps = cfg.n_rounds / per_trial
        print(
            f"local baseline: {per_trial:.3f}s/trial -> {baseline_rps:.2f} rounds/s",
            file=sys.stderr,
        )
    except Exception as e:  # keep the JSON line flowing even if baseline dies
        print(f"baseline measurement failed: {e!r}", file=sys.stderr)
        baseline_rps = None

    # North-star gate metric (BASELINE.md config 5, lossless) — skipped
    # in quick mode: off-TPU the 33-party config runs the XLA engine at
    # CPU speed, minutes of pure wait in a dev loop.
    import jax

    northstar = None
    if not quick and jax.default_backend() == "tpu":
        from qba_tpu.benchmark import NORTHSTAR, NORTHSTAR_CHUNK

        ns_cfg = QBAConfig(**NORTHSTAR, seed=0)
        try:
            from qba_tpu.benchmark import engine_description, kernel_plan

            ns_times, ns_run = _measure_jax(
                ns_cfg, reps=4, chunk_trials=NORTHSTAR_CHUNK
            )
            northstar = dict(
                _rps_stats(ns_cfg, ns_times, ns_run),
                metric="northstar_rounds_per_sec_n33_l64_d10_t1000",
                # engine/variant/packing attribution (e.g.
                # "pallas_fused/group/pack4") — the accept-path variant,
                # the fusion demotion, and the packing factor are all
                # per-machine compile probes, so the artifact must say
                # which path it timed; kernel_plan decomposes it
                # per-kernel (verdict/rebuild/fused block sizes +
                # launches per round).
                engine=engine_description(ns_cfg),
                kernel_plan=kernel_plan(ns_cfg),
                chunk_trials=NORTHSTAR_CHUNK,
            )
            try:
                northstar.update(
                    _measure_device(
                        ns_cfg, quick, chunk_trials=NORTHSTAR_CHUNK
                    )
                )
            except Exception as e:
                print(
                    f"northstar device measurement failed: {e!r}",
                    file=sys.stderr,
                )
            print(
                f"northstar: best -> {northstar['value']:.1f} rounds/s "
                f"({northstar['engine']})",
                file=sys.stderr,
            )
        except Exception as e:  # headline metric must still flow
            print(f"northstar measurement failed: {e!r}", file=sys.stderr)
            northstar = {"error": repr(e)[:300]}

    # Resource-generation gate metric: shots/s (trials x size_l list
    # positions) through the batched GF(2) stabilizer sampler — the
    # qsim phase in the BENCH artifact next to round throughput, at a
    # party count (33 -> 204 qubits) no statevector can touch.
    resource_gen = None
    try:
        from qba_tpu.benchmark import measure_resource_gen, qsim_description

        rg_cfg = QBAConfig(
            n_parties=11 if quick else 33,
            size_l=16 if quick else 64,
            n_dishonest=3 if quick else 10,
            trials=4 if quick else 8,
            seed=0,
            qsim_path="stabilizer",
        )
        rg_times, rg_shots = measure_resource_gen(
            rg_cfg, reps=2 if quick else 4
        )
        resource_gen = {
            "metric": (
                f"resource_shots_per_sec_n{rg_cfg.n_parties}"
                f"_l{rg_cfg.size_l}_stabilizer"
            ),
            "value": round(rg_shots / min(rg_times), 2),
            "unit": "shots/s",
            "median_value": round(
                rg_shots / statistics.median(rg_times), 2
            ),
            "shots_per_rep": rg_shots,
            "rep_seconds": [round(t, 4) for t in rg_times],
            "qsim": qsim_description(rg_cfg),
            "total_qubits": rg_cfg.total_qubits,
            "w": rg_cfg.w,
        }
        print(
            f"resource_gen: {resource_gen['value']:.1f} shots/s "
            f"({resource_gen['qsim']}, {rg_cfg.total_qubits} qubits)",
            file=sys.stderr,
        )
    except Exception as e:  # headline metric must still flow
        print(f"resource_gen measurement failed: {e!r}", file=sys.stderr)
        resource_gen = {"error": repr(e)[:300]}

    # Round-8 launch-overhead decomposition (pallas_mega vs
    # pallas_fused, bit-identical engines, same keys) — the BENCH_r06
    # evidence that the in-kernel round loop removes the per-round
    # fixed launch overhead.
    try:
        mega_vs_fused = _mega_vs_fused(quick)
    except Exception as e:  # comparison must never sink the gate
        print(f"mega_vs_fused measurement failed: {e!r}", file=sys.stderr)
        mega_vs_fused = None

    # Headline: the device-side median when available (slope method, no
    # tunnel fetch in the number — VERDICT r4 item 4 made the median the
    # gate); wall best-of/median stay in the JSON for continuity with
    # BENCH_r01..r04.
    headline = (
        device["device_rounds_per_sec"] if device else stats["median_value"]
    )
    # Headline-config attribution mirrors the north-star row's: the
    # engine string names the path (fusion + packing are per-machine
    # compile probes), kernel_plan decomposes it per kernel.
    from qba_tpu.benchmark import engine_description, kernel_plan

    try:
        headline_engine = engine_description(cfg)
        headline_plan = kernel_plan(cfg)
    except Exception as e:  # attribution must never sink the metric
        print(f"engine attribution failed: {e!r}", file=sys.stderr)
        headline_engine, headline_plan = None, None
    remove_decision_hook(_hook)
    # Full dispatch-decision manifest for the headline config: the
    # engine/demotion chain, resolved block plan, probe-stats delta,
    # and environment fingerprint next to the metric they explain.
    from qba_tpu.obs.manifest import collect_manifest

    try:
        manifest = collect_manifest(
            cfg,
            command="bench.py",
            decisions=decisions,
            probe_stats_before=stats_before,
        )
    except Exception as e:  # attribution must never sink the metric
        print(f"manifest collection failed: {e!r}", file=sys.stderr)
        manifest = None
    out = {
        "metric": f"protocol_rounds_per_sec_n11_l64_t{cfg.trials}",
        "value": headline,
        "unit": "rounds/s",
        "headline_source": "device_median" if device else "wall_median",
        "engine": headline_engine,
        "kernel_plan": headline_plan,
        # Two LABELED baseline ratios (VERDICT r5 weak point 2 — the
        # old single `vs_baseline` divided device-only seconds by the
        # baseline's CPU wall time, an apples-to-oranges headline):
        # the wall ratio is like-for-like (both sides carry host +
        # tunnel overhead); the device ratio is the kernels-only upper
        # bound and overstates the end-to-end speedup wherever tunnel
        # overhead matters.
        "vs_baseline_wall": (
            round(stats["median_value"] / baseline_rps, 2)
            if baseline_rps else None
        ),
        "vs_baseline_device": (
            round(device["device_rounds_per_sec"] / baseline_rps, 2)
            if (device and baseline_rps) else None
        ),
        "wall_best_value": rps,
        "median_value": stats["median_value"],
        "reps": stats["reps"],
        "rep_seconds": stats["rep_seconds"],
        **(device or {}),
        "northstar": northstar,
        "resource_gen": resource_gen,
        "mega_vs_fused": mega_vs_fused,
        "manifest": manifest,
    }
    print(json.dumps(out, default=str))


if __name__ == "__main__":
    main()
