"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (from BASELINE.json): protocol rounds/sec at nParties=11,
sizeL=64, 1000 trials (nDishonest=3 → 4 voting rounds/trial) on the jax
backend.  ``vs_baseline`` is the speedup over the message-level
pure-Python reference backend (:mod:`qba_tpu.backends.local_backend`) run
on host CPU — the in-repo stand-in for the reference's ``mpiexec`` run
(the reference itself publishes no numbers and needs MPI + qsimov,
neither available here; BASELINE.md).

Usage: ``python bench.py`` (env ``QBA_BENCH_QUICK=1`` for a small dev run).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _measure_jax(cfg, reps: int = 5) -> float:
    """Best wall-clock seconds for one full Monte-Carlo batch.

    Each rep uses fresh trial keys so a result-caching backend (the axon
    tunnel dedupes identical computations) cannot fake a 0-second run.
    """
    import jax

    from qba_tpu.backends.jax_backend import fence, run_trials, trial_keys

    fence(run_trials(cfg, trial_keys(cfg)))  # compile
    best = float("inf")
    for r in range(reps):
        keys = jax.random.split(jax.random.key(cfg.seed + 1 + r), cfg.trials)
        fence(keys)  # key generation off the clock
        t0 = time.perf_counter()
        fence(run_trials(cfg, keys))
        best = min(best, time.perf_counter() - t0)
    return best


def _measure_local(cfg, n_trials: int) -> float:
    """Per-trial seconds for the pure-Python reference backend.

    Runs in a CPU-platform subprocess: the backend issues thousands of
    tiny per-packet jax dispatches, which must not ride the TPU tunnel
    (and mirrors the reference's host-CPU execution, BASELINE.md).
    """
    import subprocess

    code = f"""
import time, jax
jax.config.update("jax_platforms", "cpu")
from qba_tpu.backends.jax_backend import trial_keys
from qba_tpu.backends.local_backend import run_trial_local
from qba_tpu.config import QBAConfig
cfg = QBAConfig(n_parties={cfg.n_parties}, size_l={cfg.size_l},
                n_dishonest={cfg.n_dishonest}, trials={cfg.trials},
                seed={cfg.seed})
keys = trial_keys(cfg)
run_trial_local(cfg, keys[0])
t0 = time.perf_counter()
for i in range({n_trials}):
    run_trial_local(cfg, keys[i % cfg.trials])
print((time.perf_counter() - t0) / {n_trials})
"""
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode != 0:
        raise RuntimeError(f"baseline subprocess failed: {proc.stderr[-500:]}")
    return float(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    from qba_tpu.compile_cache import enable_compile_cache
    from qba_tpu.config import QBAConfig

    enable_compile_cache()

    quick = os.environ.get("QBA_BENCH_QUICK") == "1"
    cfg = QBAConfig(
        n_parties=11,
        size_l=64,
        n_dishonest=3,
        trials=64 if quick else 1000,
        seed=0,
    )
    rounds_per_trial = cfg.n_rounds

    # 8 reps: the remote-tunnel result fetch has ~30 ms of run-to-run
    # jitter on top of a ~60 ms floor (and the floor itself drifts by
    # tens of ms over minutes on the shared tunnel), so extra full-work
    # reps make
    # the best-of estimate much less noisy.
    dt = _measure_jax(cfg, reps=2 if quick else 8)
    rps = cfg.trials * rounds_per_trial / dt
    print(f"jax: {cfg.trials} trials in {dt:.3f}s -> {rps:.1f} rounds/s", file=sys.stderr)

    baseline_trials = 2 if quick else 4
    try:
        per_trial = _measure_local(cfg, baseline_trials)
        baseline_rps = rounds_per_trial / per_trial
        print(
            f"local baseline: {per_trial:.3f}s/trial -> {baseline_rps:.2f} rounds/s",
            file=sys.stderr,
        )
    except Exception as e:  # keep the JSON line flowing even if baseline dies
        print(f"baseline measurement failed: {e!r}", file=sys.stderr)
        baseline_rps = None

    out = {
        "metric": f"protocol_rounds_per_sec_n11_l64_t{cfg.trials}",
        "value": round(rps, 2),
        "unit": "rounds/s",
        "vs_baseline": round(rps / baseline_rps, 2) if baseline_rps else None,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
